"""Sharded reconcile planning: one control plane, a million pods
(ISSUE 13, docs/SHARDING.md).

PR 6 made observe O(churn); the planner's remaining cost at fleet
scale is superlinear in demand × supply (`match_free` scans free
slices per gang, `claimed_by_pending` scans gangs per unit).  This
module partitions that work by **accelerator class / pool** — the
planner's natural independence boundary (delta planning already
dirties gangs per class) — runs each shard's pure plan on a capped
``concurrency.pool_executor`` worker pool, and reassembles the result
at a single merge point on the reconcile thread.

The contract (the whole point — see docs/SHARDING.md for the proof
sketch):

- **Byte-identical to serial.**  ``--reconcile-shards 0`` is the
  oracle.  A shard's clamp algebra starts from the globally-snapshotted
  fleet totals (``extra_existing_chips``), so its admissions are a
  superset-consistent prefix of serial's; the merge re-validates every
  cross-shard global (max_total_chips, the kind-wide in-flight ledger)
  and FALLS BACK TO A SERIAL PLAN the moment any shard's decision
  could have depended on another shard's consumption
  (``shard_merge_conflicts``).  Conflict-free merges reassemble
  requests/unsatisfiable/deferred in exactly serial's order.
- **CPU stays all-or-none** on one shard: CPU gangs pack into shared
  nodes (PR 6), so they, every CPU node, and the CPU spare policy ride
  a single shard.
- **Workers are pure.**  A worker receives frozen inputs (its gangs,
  nodes, pods, a per-shard ``Planner``) and returns a plan; it never
  touches controller state, metrics, or the tracer — all mutation
  happens at the merge point on the reconcile thread (the
  ActuationExecutor drain discipline, one layer up).  Crash-only: a
  worker exception degrades the pass to serial (``shard_errors``).

Partitioning is a union-find over (accelerator class, pool) keys plus
gang/group keys: a gang pinned to a class/pool stays fine-grained; an
unpinned gang (which serial ``match_free`` may bind to ANY admitting
free slice) conservatively unions every TPU class present, so sharding
degrades gracefully toward serial for unpinned fleets instead of ever
mis-partitioning one.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Sequence

import numpy as np

from tpu_autoscaler import concurrency
from tpu_autoscaler.engine.columnar import ColumnarState, claimed_units
from tpu_autoscaler.engine.fitter import free_capacity
from tpu_autoscaler.engine.planner import Planner, PoolPolicy, ScalePlan
from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    POOL_LABEL,
    TPU_RESOURCE,
    shape_by_name,
)

#: The pool dimension of the partition key: the autoscaler's own pool
#: label (k8s/objects.py ``Node.pool`` — the GKE node-pool label is
#: NOT usable here, it doubles as slice identity).  A gang that does
#: not pin it unions all of its class's pools into one component, so
#: pool-level sharding is only as fine as the workloads' own
#: placement contracts — and degrades toward per-class sharding, not
#: toward a wrong partition.
POOL_SELECTOR = POOL_LABEL

PartKey = tuple[str, str]  # (accelerator class | "cpu", pool)
CPU_PART: PartKey = ("cpu", "")

#: Substrings of planner rejection reasons that implicate a
#: cross-shard global.  A shard producing one means serial's verdict
#: (or its "(at N)" message) could differ → merge conflict.
_GLOBAL_REASONS = ("max_total_chips", "chip quota")


def node_part(node: Node) -> PartKey:
    """The partition a node's supply belongs to."""
    if not node.is_tpu:
        return CPU_PART
    return (node.tpu_accelerator or "tpu",
            node.labels.get(POOL_SELECTOR, ""))


def claimed_by_pending(units: dict[str, list[Node]],
                       pending_gangs: list[Gang],
                       pods: list[Pod],
                       columnar: ColumnarState | None = None) -> set[str]:
    """Units that currently-pending demand will bind to: NOT drainable.

    Reference parity: the reference's state machine checked "whether
    pending pods could use the node" before reclaiming (cluster.py
    §ClusterNodeState).  Without this, an idle slice can be cordoned
    in the same pass a matching gang goes Pending — the planner
    counted it as supply, so reclaiming it both strands the gang and
    forces a redundant provision.

    Pure function of its inputs (moved out of the Reconciler for
    ISSUE 13: it is the maintenance side's superlinear term, sharded
    by accelerator class alongside planning).
    """
    from tpu_autoscaler.engine.planner import _slice_satisfies

    claimed: set[str] = set()
    tpu_gangs = [g for g in pending_gangs if g.requests_tpu]
    cpu_pods = [p for g in pending_gangs if not g.requests_tpu
                for p in g.pods]
    if columnar is not None and columnar.n_pods == len(pods):
        try:
            got = claimed_units(columnar, units, tpu_gangs, cpu_pods,
                                _slice_satisfies)
            if got is not None:
                return got
        except Exception:  # noqa: BLE001 — crash-only: a columnar bug
            # degrades the claim scan to the Python oracle loop below.
            import logging

            logging.getLogger(__name__).exception(
                "columnar claim scan failed; Python fallback")
    for unit_id, unit_nodes in units.items():
        if unit_nodes[0].is_tpu:
            if any(_slice_satisfies(unit_nodes, g) for g in tpu_gangs):
                claimed.add(unit_id)
        elif cpu_pods:
            # Count cordoned nodes: a DRAINING unit's nodes are
            # unschedulable by construction, and the whole point of
            # the claim check is to cancel that drain when pending
            # demand fits it (mirrors _slice_satisfies, which also
            # ignores the cordon flag for TPU units).  With no pending
            # CPU demand the per-unit free-capacity scan is skipped
            # outright (1M-pod audit: it was O(cpu units × pods) of
            # pure waste on TPU-only demand).
            free = free_capacity(unit_nodes, pods,
                                 include_unschedulable=True)
            if any(node.admits(p) and p.resources.fits_in(cap)
                   for p in cpu_pods
                   for node in unit_nodes
                   for name, cap in free.items()
                   if name == node.name):
                claimed.add(unit_id)
    return claimed


# ---- union-find partitioning ------------------------------------------ #


class _UnionFind:
    def __init__(self) -> None:
        self._parent: dict = {}

    def find(self, x):
        parent = self._parent
        root = x
        while parent.setdefault(root, root) != root:
            root = parent[root]
        while parent[x] != root:  # path compression
            parent[x], x = root, parent[x]
        return root

    def union(self, a, b) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self._parent[rb] = ra


@dataclasses.dataclass
class _Partition:
    """One pass's partition: which shard (bucket) owns which keys."""

    n_buckets: int
    bucket_of_part: dict[PartKey, int]
    bucket_of_gang: dict[tuple, int]          # gang key -> bucket
    cpu_bucket: int
    #: gang key -> index in the input gang list: the serial planner's
    #: emission anchor for the byte-identical merge (a cohort is
    #: created at its first UNMATCHED member, so a request's min
    #: member index IS its serial emission position; the multi-entry-
    #: per-group case where that breaks down conflicts into the
    #: serial oracle instead — see _merge).
    order: dict[tuple, int]
    #: gang key -> multislice group key (None for solos), for the
    #: merge's one-entry-per-group check.
    group_of: dict[tuple, tuple | None]

    def bucket_of_node(self, node: Node) -> int | None:
        return self.bucket_of_part.get(node_part(node))


def _candidate_parts(gang: Gang, accels_present: Sequence[str],
                     pools_by_accel: dict[str, list[PartKey]],
                     candidate_accels: Callable[[Gang], tuple[str, ...]]
                     ) -> list[PartKey]:
    """Every partition whose nodes this gang could match or bind.

    Conservative over-approximation (the digest rule, applied to
    partitioning): missing a partition the serial planner could have
    matched would silently change the plan, so an unpinned gang takes
    every TPU class present — serial ``match_free`` admission is
    selector-based and an unpinned pod admits any tolerated slice.
    """
    if not gang.requests_tpu:
        return [CPU_PART]
    selectors = gang.node_selectors
    accel_pin = selectors.get(ACCELERATOR_LABEL)
    pool_pin = selectors.get(POOL_SELECTOR)
    if accel_pin is not None:
        accels: list[str] = [accel_pin]
    else:
        accels = list(accels_present)
        for a in candidate_accels(gang):
            if a not in accels:
                accels.append(a)
    parts: list[PartKey] = []
    for a in accels:
        if pool_pin is not None:
            parts.append((a, pool_pin))
        else:
            parts.extend(pools_by_accel.get(a) or [(a, "")])
    return parts or [("tpu", "")]


def partition(gangs: list[Gang],
              advisory: Sequence[tuple[Gang, str]],
              nodes: list[Node],
              policy: PoolPolicy,
              candidate_accels: Callable[[Gang], tuple[str, ...]],
              n_shards: int) -> _Partition:
    """Group (accel class, pool) keys into components and assign them
    to at most ``n_shards`` buckets, deterministically.

    Components are the transitive closure of "could interact through
    supply": each gang unions its candidate partitions (plus its gang
    and multislice-group keys, so advisory demand and cohort siblings
    co-locate with their organic twins); each advisory entry unions
    its exact replacement shape's class; each spare-slice shape unions
    its class's pools (the spare scan ranges over them).  CPU is one
    partition by construction.
    """
    uf = _UnionFind()
    parts_present: list[PartKey] = []
    seen: set[PartKey] = set()
    for n in nodes:
        key = node_part(n)
        if key not in seen:
            seen.add(key)
            parts_present.append(key)
    uf.find(CPU_PART)
    if CPU_PART not in seen:
        seen.add(CPU_PART)
        parts_present.append(CPU_PART)
    accels_present: list[str] = []
    pools_by_accel: dict[str, list[PartKey]] = {}
    for key in parts_present:
        accel = key[0]
        if key == CPU_PART:
            continue
        if accel not in pools_by_accel:
            pools_by_accel[accel] = []
            accels_present.append(accel)
        pools_by_accel[accel].append(key)
        # All pools of one class start independent; gangs/spares that
        # range over the class union them below.
        uf.find(key)

    order: dict[tuple, int] = {}
    group_of: dict[tuple, tuple | None] = {}
    for i, gang in enumerate(gangs):
        group = gang.multislice_group_key
        order[gang.key] = i
        group_of[gang.key] = group
        tokens: list = [("gang", gang.key)]
        if group is not None:
            tokens.append(("gang", group))
        tokens.extend(_candidate_parts(gang, accels_present,
                                       pools_by_accel, candidate_accels))
        for tok in tokens[1:]:
            uf.union(tokens[0], tok)
    for gang, shape_name in advisory:
        tokens = [("gang", gang.key)]
        group = gang.multislice_group_key
        if group is not None:
            tokens.append(("gang", group))
        try:
            accel = shape_by_name(shape_name).accelerator_type
        except KeyError:
            accel = None
        if accel is not None:
            tokens.extend(pools_by_accel.get(accel) or [(accel, "")])
        uf.find(tokens[0])
        for tok in tokens[1:]:
            uf.union(tokens[0], tok)
    for shape_name in policy.spare_slices:
        try:
            accel = shape_by_name(shape_name).accelerator_type
        except KeyError:
            continue
        pools = pools_by_accel.get(accel) or [(accel, "")]
        for key in pools[1:]:
            uf.union(pools[0], key)
        uf.find(pools[0])

    # Components -> buckets, deterministically: sort components by
    # their smallest member partition key, round-robin into buckets.
    roots: dict = {}
    for key in list(uf._parent):
        roots.setdefault(uf.find(key), []).append(key)
    components = sorted(
        roots.items(),
        key=lambda kv: min([k for k in kv[1] if isinstance(k, tuple)
                            and len(k) == 2 and k[0] != "gang"]
                           or [("", "")]))
    bucket_of_root: dict = {}
    for i, (root, _members) in enumerate(components):
        bucket_of_root[root] = i % max(1, n_shards)
    bucket_of_part = {key: bucket_of_root[uf.find(key)]
                      for key in parts_present}
    bucket_of_gang = {g.key: bucket_of_root[uf.find(("gang", g.key))]
                      for g in gangs}
    for gang, _shape in advisory:
        bucket_of_gang.setdefault(
            gang.key, bucket_of_root[uf.find(("gang", gang.key))])
    return _Partition(
        n_buckets=max(1, n_shards),
        bucket_of_part=bucket_of_part,
        bucket_of_gang=bucket_of_gang,
        cpu_bucket=bucket_of_part[CPU_PART],
        order=order, group_of=group_of)


# ---- the worker -------------------------------------------------------- #


@dataclasses.dataclass
class _ShardWork:
    """One worker's frozen inputs (built on the reconcile thread)."""

    index: int
    planner: Planner                 # per-shard policy (spares filtered)
    gangs: list[Gang]
    advisory: list[tuple[Gang, str]]
    nodes: list[Node]
    pods: list[Pod]
    in_flight: Sequence
    gen_overrides: dict
    extra_existing_chips: int
    columnar: ColumnarState | None = None  # per-shard column slice


@dataclasses.dataclass
class _ShardOutcome:
    index: int
    plan: ScalePlan
    seconds: float
    planned_tpu_chips: int
    items: int                       # gangs + advisory (load balance)


def _plan_shard(work: _ShardWork) -> _ShardOutcome:
    """Worker body: one pure planner call over the shard's slice of
    the world.  Touches nothing but its arguments."""
    t0 = time.perf_counter()
    plan = work.planner.plan(
        work.gangs, work.nodes, work.pods, work.in_flight,
        generation_overrides=work.gen_overrides,
        advisory_gangs=work.advisory,
        extra_existing_chips=work.extra_existing_chips,
        columnar=work.columnar)
    planned = sum(shape_by_name(r.shape_name).chips * r.count
                  for r in plan.requests if r.kind == "tpu-slice")
    return _ShardOutcome(index=work.index, plan=plan,
                         seconds=time.perf_counter() - t0,
                         planned_tpu_chips=planned,
                         items=len(work.gangs) + len(work.advisory))


def _claim_shard(units: dict[str, list[Node]], gangs: list[Gang],
                 pods: list[Pod]) -> tuple[set[str], float]:
    t0 = time.perf_counter()
    claimed = claimed_by_pending(units, gangs, pods)
    return claimed, time.perf_counter() - t0


# ---- the sharder ------------------------------------------------------- #


class ShardConflict(Exception):
    """A cross-shard global invalidated the merge (internal signal:
    the caller re-plans serially)."""


class ShardedPlanner:
    """Fan-out/merge driver, owned by the Controller and called ONLY
    from the reconcile thread.  Holds the worker pool, the previous
    partition assignment (rebalance detection), and the metrics
    bridge; workers receive none of it."""

    def __init__(self, shards: int, planner: Planner, metrics=None,
                 min_gangs: int = 16, max_workers: int | None = None):
        self.shards = max(1, int(shards))
        self.planner = planner
        self.metrics = metrics
        self.min_gangs = min_gangs
        self._max_workers = (max_workers if max_workers
                             else min(self.shards,
                                      max(2, os.cpu_count() or 2)))
        self._pool = None
        self._assignment: dict[PartKey, int] = {}
        self.last_info: dict = {}

    # -- lifecycle ----------------------------------------------------

    def _ensure_pool(self):
        if self._pool is None:
            self._pool = concurrency.pool_executor(
                self._max_workers, thread_name_prefix="shard")
        return self._pool

    def close(self) -> None:
        """Shut the worker pool down.  Idempotent; the next plan()
        would lazily rebuild it (crash-only symmetry)."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    def _inc(self, name: str, value: float = 1) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, value)

    # -- shardability -------------------------------------------------

    def _serial_reason(self, gangs: list[Gang],
                       advisory: Sequence[tuple[Gang, str]],
                       policy: PoolPolicy) -> str | None:
        """Why this pass must plan serially, or None.

        fair_share re-sorts admissions across the whole demand set and
        namespace quotas couple namespaces across classes — both make
        cross-shard order load-bearing, so they serialize (the same
        rule delta planning applies).  Hard-constrained pending CPU
        pods serialize because predicate placement may read placements
        on nodes outside the CPU shard (zone-level anti-affinity).
        """
        if policy.fair_share:
            return "fair_share"
        if policy.namespace_chip_quota:
            return "namespace_quota"
        if len(gangs) + len(advisory) < self.min_gangs:
            return "small_pass"
        from tpu_autoscaler.k8s.scheduling import (
            has_scheduling_constraints,
        )

        for gang in gangs:
            if not gang.requests_tpu \
                    and any(has_scheduling_constraints(p)
                            for p in gang.pods):
                return "constrained_cpu"
        return None

    # -- planning -----------------------------------------------------

    def plan(self, gangs: list[Gang], nodes: list[Node],
             pods: list[Pod], in_flight: Sequence = (),
             generation_overrides: dict | None = None,
             advisory_gangs: Sequence[tuple[Gang, str]] = (),
             candidate_accels: Callable[[Gang], tuple[str, ...]] = (
                 lambda g: ()),
             columnar: ColumnarState | None = None,
             ) -> ScalePlan:
        """The sharded twin of ``Planner.plan`` — byte-identical
        output, with ``self.last_info`` describing how the pass ran
        (for the pass record's ``planning.sharding`` section).
        ``columnar`` (engine/columnar.py) rides along: serial
        fallbacks hand it to the serial planner whole; the sharded
        path slices it per shard with ``ColumnarState.take``."""
        advisory = list(advisory_gangs)
        reason = self._serial_reason(gangs, advisory,
                                     self.planner.policy)
        if reason is not None:
            self._inc("shard_serial_fallbacks")
            self._set_balance(1.0, 0)
            self.last_info = {"mode": "serial", "why": reason}
            return self.planner.plan(
                gangs, nodes, pods, in_flight,
                generation_overrides=generation_overrides,
                advisory_gangs=advisory, columnar=columnar)
        try:
            plan, info = self._plan_sharded(
                gangs, nodes, pods, in_flight,
                generation_overrides or {}, advisory, candidate_accels,
                columnar)
            self.last_info = info
            return plan
        except ShardConflict as e:
            self._inc("shard_merge_conflicts")
            self._set_balance(1.0, 0)
            self.last_info = {"mode": "serial", "why": "merge_conflict",
                              "conflict": str(e)}
        except Exception:  # noqa: BLE001 — crash-only: a sharding bug
            # degrades the pass to the serial oracle, never breaks
            # scaling.  Counted and logged; the plan below is the
            # same pure function the serial path always ran.
            import logging

            self._inc("shard_errors")
            self._set_balance(1.0, 0)
            logging.getLogger(__name__).exception(
                "sharded planning failed; serial fallback this pass")
            self.last_info = {"mode": "serial", "why": "shard_error"}
        return self.planner.plan(
            gangs, nodes, pods, in_flight,
            generation_overrides=generation_overrides,
            advisory_gangs=advisory, columnar=columnar)

    def _plan_sharded(self, gangs, nodes, pods, in_flight,
                      gen_overrides, advisory, candidate_accels,
                      columnar=None):
        policy = self.planner.policy
        part = partition(gangs, advisory, nodes, policy,
                         candidate_accels, self.shards)
        self._note_rebalance(part)

        # Slice the world.  Node/pod routing is one dict lookup per
        # object; order within each shard preserves the input order
        # (free-slice iteration order is part of the byte-identity
        # contract).
        shard_nodes: list[list[Node]] = [[] for _ in
                                         range(part.n_buckets)]
        node_bucket: dict[str, int] = {}
        node_rows: list[list[int]] = [[] for _ in range(part.n_buckets)]
        node_bucket_arr = np.empty(len(nodes), np.int32)
        existing_total = 0
        shard_chips = [0] * part.n_buckets
        for row, n in enumerate(nodes):
            b = part.bucket_of_node(n)
            if b is None:
                b = part.cpu_bucket
            shard_nodes[b].append(n)
            node_bucket[n.name] = b
            node_rows[b].append(row)
            node_bucket_arr[row] = b
            if n.is_tpu:
                chips = int(n.allocatable.get(TPU_RESOURCE))
                existing_total += chips
                shard_chips[b] += chips
        gang_bucket = part.bucket_of_gang
        cstate: ColumnarState | None = None
        if columnar is not None:
            try:
                if columnar.attachable(nodes, pods):
                    cstate = columnar
            except Exception:  # noqa: BLE001 — crash-only: a stale or
                # torn columnar state only forfeits the fast routing;
                # the dict-lookup loop below is always correct.
                cstate = None
        shard_pods: list[list[Pod]] = [[] for _ in range(part.n_buckets)]
        pod_rows: list[np.ndarray] = []
        if cstate is not None:
            # Vectorized pod routing over the columnar view: a bound
            # pod follows its node's bucket; an unbound pod follows
            # its gang's bucket; pods bound to unknown nodes (row -1)
            # and gang-less strays drop, exactly like the dict path.
            bucket = np.full(cstate.n_pods, -1, np.int32)
            bound = cstate.p_has_node & (cstate.p_node_row >= 0)
            bucket[bound] = node_bucket_arr[cstate.p_node_row[bound]]
            gb = np.full(len(cstate.gang_keys) + 1, -1, np.int32)
            for gi, gkey in enumerate(cstate.gang_keys):
                got = gang_bucket.get(gkey)
                if got is not None:
                    gb[gi] = got
            unbound = ~cstate.p_has_node
            bucket[unbound] = gb[cstate.p_gang[unbound]]
            for b in range(part.n_buckets):
                rows = np.flatnonzero(bucket == b)
                pod_rows.append(rows)
                shard_pods[b] = [pods[i] for i in rows]
        else:
            for p in pods:
                if p.node_name:
                    b = node_bucket.get(p.node_name)
                else:
                    b = gang_bucket.get(p.gang_key)
                if b is not None:
                    shard_pods[b].append(p)
        shard_gangs: list[list[Gang]] = [[] for _ in
                                         range(part.n_buckets)]
        for g in gangs:
            shard_gangs[gang_bucket[g.key]].append(g)
        shard_adv: list[list] = [[] for _ in range(part.n_buckets)]
        advisory_index: dict[tuple, int] = {}
        for i, (g, shape_name) in enumerate(advisory):
            advisory_index.setdefault(g.key, i)
            shard_adv[gang_bucket[g.key]].append((g, shape_name))

        # Per-shard policy: spare shapes live with their class's
        # shard; the CPU extras (spare/over-provision nodes) live
        # with the CPU shard — any other assignment would plan spare
        # capacity against a world that cannot see the existing one.
        works: list[_ShardWork] = []
        for b in range(part.n_buckets):
            spares = {name: want
                      for name, want in policy.spare_slices.items()
                      if self._spare_bucket(name, part) == b}
            is_cpu = b == part.cpu_bucket
            busy = bool(shard_gangs[b] or shard_adv[b] or spares
                        or (is_cpu and policy.spare_nodes > 0))
            if not busy:
                continue
            shard_policy = dataclasses.replace(
                policy, spare_slices=spares,
                spare_nodes=policy.spare_nodes if is_cpu else 0,
                over_provision_nodes=(policy.over_provision_nodes
                                      if is_cpu else 0))
            shard_cols: ColumnarState | None = None
            if cstate is not None:
                try:
                    shard_cols = cstate.take(
                        np.asarray(node_rows[b], np.int64), pod_rows[b])
                except Exception:  # noqa: BLE001 — crash-only: the
                    # shard just plans on Python objects instead.
                    shard_cols = None
            works.append(_ShardWork(
                index=b, planner=Planner(shard_policy),
                gangs=shard_gangs[b], advisory=shard_adv[b],
                nodes=shard_nodes[b], pods=shard_pods[b],
                in_flight=in_flight, gen_overrides=gen_overrides,
                extra_existing_chips=existing_total - shard_chips[b],
                columnar=shard_cols))

        outcomes = self._run(works, _plan_shard)
        plan = self._merge(outcomes, in_flight, policy,
                           existing_total, part.order, part.group_of,
                           advisory_index, part.cpu_bucket)
        balance = self._balance([o.items for o in outcomes])
        self._set_balance(balance, len(outcomes))
        if self.metrics is not None:
            for o in outcomes:
                self.metrics.observe("shard_pass_seconds", o.seconds)
        return plan, {
            "mode": "sharded", "shards": len(outcomes),
            "items": [o.items for o in outcomes],
            "balance": round(balance, 3),
        }

    # -- maintenance-side sharding ------------------------------------

    def claimed_by_pending(self, units: dict[str, list[Node]],
                           pending_gangs: list[Gang],
                           pods: list[Pod],
                           candidate_accels,
                           columnar: ColumnarState | None = None
                           ) -> set[str]:
        """Sharded twin of :func:`claimed_by_pending` — the maintain
        pass's superlinear term, partitioned exactly like planning (a
        unit can only be claimed by gangs of its own component).
        Crash-only: any failure degrades to the serial scan.

        When a :class:`ColumnarState` aligned with ``pods`` is handed
        in, the vectorized claim scan (engine/columnar.py
        ``claimed_units``) answers serially — it is already faster
        than the sharded Python fan-out, so no partition is needed."""
        if columnar is not None and columnar.n_pods == len(pods):
            try:
                from tpu_autoscaler.engine.planner import _slice_satisfies

                tpu_gangs = [g for g in pending_gangs if g.requests_tpu]
                cpu_pods = [p for g in pending_gangs
                            if not g.requests_tpu for p in g.pods]
                got = claimed_units(columnar, units, tpu_gangs,
                                    cpu_pods, _slice_satisfies)
                if got is not None:
                    return got
            except Exception:  # noqa: BLE001 — crash-only: fall back
                # to the sharded Python scan below.
                import logging

                logging.getLogger(__name__).exception(
                    "columnar claim scan failed; sharded fallback")
        try:
            # One representative node per unit is enough for the
            # partition to learn which (class, pool) keys exist — a
            # unit's hosts share both labels by construction.
            rep_nodes = [uns[0] for uns in units.values()]
            part = partition(pending_gangs, (), rep_nodes,
                             self.planner.policy, candidate_accels,
                             self.shards)
            shard_units: list[dict] = [{} for _ in range(part.n_buckets)]
            for uid, unit_nodes in units.items():
                b = part.bucket_of_part.get(node_part(unit_nodes[0]))
                if b is None:
                    b = part.cpu_bucket
                shard_units[b][uid] = unit_nodes
            shard_gangs: list[list[Gang]] = [[] for _ in
                                             range(part.n_buckets)]
            for g in pending_gangs:
                shard_gangs[part.bucket_of_gang[g.key]].append(g)
            works = [(b, shard_units[b], shard_gangs[b])
                     for b in range(part.n_buckets)
                     if shard_units[b]]
            pool = self._ensure_pool()
            futures = [pool.submit(_claim_shard, u, g, pods)
                       for _b, u, g in works]
            claimed: set[str] = set()
            for sub, seconds in self._collect(futures):
                claimed |= sub
                if self.metrics is not None:
                    self.metrics.observe("shard_pass_seconds", seconds)
            return claimed
        except Exception:  # noqa: BLE001 — crash-only: degrade to the
            # serial scan; a sharding bug must never break maintenance.
            import logging

            self._inc("shard_errors")
            logging.getLogger(__name__).exception(
                "sharded claim scan failed; serial fallback this pass")
            return claimed_by_pending(units, pending_gangs, pods)

    # -- internals ----------------------------------------------------

    def _spare_bucket(self, shape_name: str, part: _Partition) -> int:
        try:
            accel = shape_by_name(shape_name).accelerator_type
        except KeyError:
            return part.cpu_bucket
        for key, b in part.bucket_of_part.items():
            if key[0] == accel:
                return b
        return part.cpu_bucket

    def _run(self, works: list[_ShardWork], fn) -> list[_ShardOutcome]:
        if not works:
            return []
        if len(works) == 1:
            # One busy shard: the fan-out would buy one context switch.
            return [fn(works[0])]
        pool = self._ensure_pool()
        futures = [pool.submit(fn, w) for w in works]
        return self._collect(futures)

    @staticmethod
    def _collect(futures: list) -> list:
        """Barrier-wait on the fan-out.  Under the DeterministicScheduler
        a raw ``Future.result()`` blocks on a real condition variable
        that is not a schedule point — poll ``done()`` through the
        scheduler's step instead, exactly like the harness's own pool
        tests (testing/sched.py SchedPool)."""
        sched = concurrency.active_scheduler()
        if sched is not None:
            while not all(f.done() for f in futures):
                sched.step()
        return [f.result() for f in futures]

    def _note_rebalance(self, part: _Partition) -> None:
        if self._assignment and any(
                self._assignment.get(k, b) != b
                for k, b in part.bucket_of_part.items()):
            self._inc("shard_rebalance_events")
        self._assignment = dict(part.bucket_of_part)

    def _balance(self, items: list[int]) -> float:
        """Mean-over-CONFIGURED-shards / max busy load.  The
        denominator is the configured shard count, not the busy count:
        one component owning all demand must read IMBALANCED (that is
        the shard-imbalance alert's motivating case — sharding buys
        nothing there), not a perfect 1.0 (review-found)."""
        if not items or max(items) <= 0:
            return 1.0
        return min(1.0, (sum(items) / self.shards) / max(items))

    def _set_balance(self, balance: float, count: int) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("shard_balance", round(balance, 4))
            self.metrics.set_gauge("shard_count", count)

    # -- the merge point ----------------------------------------------

    def _merge(self, outcomes: list[_ShardOutcome],
               in_flight, policy: PoolPolicy, existing_total: int,
               order: dict, group_of: dict, advisory_index: dict,
               cpu_bucket: int) -> ScalePlan:
        """Reassemble one serial-identical plan, or raise
        :class:`ShardConflict` when a cross-shard global could have
        changed any shard's decision.

        Re-validated globals (snapshotted at fan-out): the
        max_total_chips clamp against the fleet total plus the
        kind-wide in-flight ledger.  Any clamp-flavored rejection or
        deferral inside a shard also conflicts — serial's rejection
        message embeds the cross-shard running total, so even an
        "identical" verdict would not be byte-identical.

        Ordering: serial creates a cohort at its first UNMATCHED
        member and emits FIFO, so a request's min member index IS its
        serial position — EXCEPT when one multislice group yields
        multiple separate entries (a heterogeneous jobset degrading
        to solo units, or FitError'd members beside an emitted unit):
        serial clusters those at the cohort's creation point, which
        the merge cannot reconstruct, so that case conflicts into the
        serial oracle (review-found).
        """
        inflight_chips = sum(shape_by_name(f.shape_name).chips * f.count
                             for f in in_flight
                             if f.kind == "tpu-slice")
        total_planned = sum(o.planned_tpu_chips for o in outcomes)
        if (existing_total + inflight_chips + total_planned
                > policy.max_total_chips):
            raise ShardConflict(
                f"planned {total_planned} chips across shards exceeds "
                f"max_total_chips={policy.max_total_chips} headroom")
        for o in outcomes:
            if o.plan.deferred:
                raise ShardConflict(
                    f"shard {o.index} deferred advisory demand at a "
                    f"global clamp")
            for _gang, why in o.plan.unsatisfiable:
                if any(s in why for s in _GLOBAL_REASONS):
                    raise ShardConflict(
                        f"shard {o.index} rejected at a global clamp: "
                        f"{why}")

        spare_rank = {name: i for i, name
                      in enumerate(policy.spare_slices)}
        organic: list[tuple[int, object]] = []
        adv: list[tuple[int, object]] = []
        spares: list[tuple[tuple, object]] = []
        cpu: list = []
        #: multislice group key -> distinct emitted entries (requests
        #: or unsatisfiable): more than one means the min-index sort
        #: cannot reproduce serial's cohort clustering — conflict.
        group_entries: dict[tuple, int] = {}

        def note_group(gang_key) -> None:
            group = group_of.get(gang_key)
            if group is not None:
                group_entries[group] = group_entries.get(group, 0) + 1

        for o in outcomes:
            for req in o.plan.requests:
                if req.kind == "cpu-node":
                    cpu.append(req)
                    continue
                section = _section_of(req.reason)
                if section == "organic":
                    members = req.gang_keys or (req.gang_key,)
                    keys = [order[k] for k in members if k in order]
                    if not keys:
                        raise ShardConflict(
                            f"request for unknown gang {req.gang_key}")
                    note_group((req.gang_keys or (req.gang_key,))[0])
                    organic.append((min(keys), req))
                elif section == "advisory":
                    idx = advisory_index.get(req.gang_key)
                    if idx is None:
                        raise ShardConflict(
                            f"advisory request for unknown key "
                            f"{req.gang_key}")
                    adv.append((idx, req))
                elif section == "spare":
                    spares.append(
                        ((spare_rank.get(req.shape_name, 1 << 30),
                          o.index), req))
                else:
                    raise ShardConflict(
                        f"unclassifiable request reason {req.reason!r}")
        organic.sort(key=lambda kv: kv[0])
        adv.sort(key=lambda kv: kv[0])
        spares.sort(key=lambda kv: kv[0])

        merged = ScalePlan()
        merged.requests = ([r for _k, r in organic]
                           + [r for _k, r in adv]
                           + [r for _k, r in spares] + cpu)
        tpu_unsat: list[tuple[int, tuple]] = []
        cpu_unsat: list[tuple] = []
        for o in outcomes:
            for entry in o.plan.unsatisfiable:
                gang = entry[0]
                if o.index == cpu_bucket and not gang.requests_tpu:
                    cpu_unsat.append(entry)
                elif gang.key in order:
                    note_group(gang.key)
                    tpu_unsat.append((order[gang.key], entry))
                else:
                    raise ShardConflict(
                        f"unsatisfiable entry for unknown gang "
                        f"{gang.key}")
        split_groups = [g for g, n in group_entries.items() if n > 1]
        if split_groups:
            raise ShardConflict(
                f"multislice group(s) {split_groups[:2]} produced "
                f"multiple separate entries; serial clusters them at "
                f"cohort creation — re-planning serially")
        tpu_unsat.sort(key=lambda kv: kv[0])
        merged.unsatisfiable = ([e for _k, e in tpu_unsat] + cpu_unsat)
        return merged


def _section_of(reason: str) -> str:
    """Which serial plan section a request came from, by the planner's
    own reason strings (pinned by tests/test_shard.py so a reworded
    reason fails loudly there, not silently here; an unknown prefix
    conflicts the merge into the serial oracle either way)."""
    if reason.startswith(("gang ", "multislice jobset ")):
        return "organic"
    if reason.startswith(("slice repair: ", "predictive prewarm: ")):
        return "advisory"
    if reason.startswith("spare slice policy"):
        return "spare"
    return "unknown"
