"""Level-triggered wake-up for the reconcile loop.

The reference's detection latency was bounded by its poll period
(main.py --sleep, default ~60 s; SURVEY.md §7).  Here a background thread
holds a pod watch open against the apiserver and pokes an Event whenever
anything changes; the loop sleeps on that Event with the poll interval as a
*fallback*, so detection is near-instant when the watch is healthy and no
worse than the reference when it is not (crash-only: watch errors just mean
we fall back to polling until the watch re-establishes).
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger(__name__)


class WatchTrigger(threading.Thread):
    def __init__(self, client, wake: threading.Event,
                 timeout_seconds: int = 60):
        super().__init__(daemon=True, name="pod-watch")
        self._client = client
        self._wake = wake
        self._timeout = timeout_seconds
        self._stopped = threading.Event()

    def stop(self) -> None:
        self._stopped.set()

    def run(self) -> None:
        while not self._stopped.is_set():
            try:
                for _event in self._client.watch_pods(self._timeout):
                    self._wake.set()
                    if self._stopped.is_set():
                        return
            except Exception:  # noqa: BLE001 — degrade to poll-only
                log.warning("pod watch failed; retrying", exc_info=True)
                if self._stopped.wait(5.0):
                    return
