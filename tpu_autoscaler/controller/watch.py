"""Level-triggered wake-up for the reconcile loop.

The reference's detection latency was bounded by its poll period
(main.py --sleep, default ~60 s; SURVEY.md §7).  Here a background thread
holds a pod watch open against the apiserver and pokes an Event whenever
a pod actually changes; the loop sleeps on that Event with the poll
interval as a *fallback*, so detection is near-instant when the watch is
healthy and no worse than the reference when it is not (crash-only: watch
errors just mean we fall back to polling until the watch re-establishes).

Since ISSUE 2 the production loop (``Controller.run_forever``) uses the
informer (``k8s/informer.py``), which generalizes this trigger into a
delta-applying object cache — same wake semantics, same backoff/410
discipline — and additionally serves the reconciler's observations.
``WatchTrigger`` remains the minimal wake-only building block.

Hardening (VERDICT r1 item 6):

- reconnects resume from the last seen ``resourceVersion`` (with
  bookmarks requested to keep it fresh) instead of re-listing the world;
  a 410 Gone resets it and the next watch starts from "now";
- failures back off exponentially with jitter (base 1 s, cap 60 s) and
  are counted in the ``watch_failures`` metric; only the first failure
  of a streak logs at WARNING — the rest at DEBUG, so a flapping
  apiserver cannot spam one warning per retry;
- only ADDED/MODIFIED/DELETED events set the wake flag: BOOKMARK and
  ERROR events carry no reconcile-relevant state change.
"""

from __future__ import annotations

import logging
import random
import threading

from tpu_autoscaler import concurrency
from tpu_autoscaler.backoff import (
    WATCH_BACKOFF_BASE_S as BACKOFF_BASE_S,
    WATCH_BACKOFF_CAP_S as BACKOFF_CAP_S,
    watch_backoff_seconds,
)

log = logging.getLogger(__name__)

_RELEVANT_TYPES = frozenset({"ADDED", "MODIFIED", "DELETED"})


class WatchTrigger(concurrency.Thread):
    def __init__(self, client, wake: threading.Event,
                 timeout_seconds: int = 60, metrics=None,
                 rng: random.Random | None = None):
        super().__init__(daemon=True, name="pod-watch")
        self._client = client
        self._wake = wake
        self._timeout = timeout_seconds
        self._stopped = concurrency.Event()
        self._metrics = metrics
        self._rng = rng or random.Random()
        self._resource_version: str | None = None
        self._failure_streak = 0

    def stop(self) -> None:
        self._stopped.set()

    # -- internals, factored for testability -----------------------------

    def _backoff_seconds(self) -> float:
        """Exponential with full jitter: uniform(0, min(cap, base*2^n))."""
        return watch_backoff_seconds(self._failure_streak, self._rng)

    def _handle_event(self, event: dict) -> None:
        etype = event.get("type")
        obj = event.get("object") or {}
        if etype == "ERROR":
            # Expired resourceVersion (410 Gone) arrives as an ERROR
            # event; drop the cursor so the next watch starts from "now".
            if obj.get("code") == 410:
                self._resource_version = None
            raise _WatchError(str(obj.get("message", "watch ERROR event")))
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        if rv:
            self._resource_version = rv
        if etype in _RELEVANT_TYPES:
            self._wake.set()
        # BOOKMARK: cursor refreshed above; nothing to reconcile.

    def _watch_once(self) -> None:
        # Older fakes/clients may not take resource_version; only pass it
        # when supported so the trigger works against any KubeClient.
        try:
            events = self._client.watch_pods(
                self._timeout, resource_version=self._resource_version)
        except TypeError:
            events = self._client.watch_pods(self._timeout)
        for event in events:
            self._handle_event(event)
            if self._stopped.is_set():
                return

    def run(self) -> None:
        while not self._stopped.is_set():
            try:
                self._watch_once()
                self._failure_streak = 0  # clean server-side close
            except Exception as e:  # noqa: BLE001 — degrade to poll-only
                self._failure_streak += 1
                if self._metrics is not None:
                    self._metrics.inc("watch_failures")
                level = (logging.WARNING if self._failure_streak == 1
                         else logging.DEBUG)
                log.log(level, "pod watch failed (streak %d): %s; "
                        "retrying with backoff", self._failure_streak, e,
                        exc_info=self._failure_streak == 1)
                if self._stopped.wait(self._backoff_seconds()):
                    return


class _WatchError(RuntimeError):
    """An ERROR event on an otherwise-healthy stream (e.g. 410 Gone)."""
