from tpu_autoscaler.controller.reconciler import Controller, ControllerConfig

__all__ = ["Controller", "ControllerConfig"]
