"""Kubernetes object model (L3a).

TPU-native analog of the reference's ``autoscaler/kube.py``: value-object
wrappers over raw API payload dicts with resource arithmetic, selector
matching, pending-pod detection, and gang/JobSet awareness.  Unlike the
reference (pykube objects), these wrappers take plain dicts so every layer
is constructible from JSON fixtures, and all API verbs go through an
abstract client interface (``tpu_autoscaler.k8s.client``) that the fake
apiserver also implements.
"""

from tpu_autoscaler.k8s.resources import ResourceVector, parse_quantity
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.gangs import Gang, group_into_gangs

__all__ = [
    "Gang",
    "Node",
    "Pod",
    "ResourceVector",
    "group_into_gangs",
    "parse_quantity",
]
