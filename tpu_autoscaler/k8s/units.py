"""Supply-unit grouping, shared by the reconciler and the status command.

TPU hosts group by slice id (all hosts of one slice are one atomic unit).
CPU nodes are each their own unit, keyed by our explicit slice label if
present else the node name — deliberately NOT the GKE nodepool label,
which would collapse a whole CPU pool into one drain/delete unit.
"""

from __future__ import annotations

from tpu_autoscaler.k8s.objects import Node
from tpu_autoscaler.topology.catalog import SLICE_ID_LABEL


def unit_key_of(node: Node) -> str:
    """The supply-unit key one node belongs to — the single definition
    shared by :func:`group_supply_units`, the informer's pool fold
    (``CapacityView``) and the columnar planner core's unit grouping,
    so the three can never drift."""
    if node.is_tpu and node.slice_id:
        return node.slice_id
    return node.labels.get(SLICE_ID_LABEL) or node.name


def group_supply_units(nodes: list[Node]) -> dict[str, list[Node]]:
    units: dict[str, list[Node]] = {}
    for node in nodes:
        units.setdefault(unit_key_of(node), []).append(node)
    return units
