"""Builders for node payloads the fake actuator materializes.

Kept in the package (not test fixtures) because the fake actuator is a
product surface: it powers `--fake-cloud` demo mode and the e2e loop tests.
Payload shape mirrors what GKE writes for real TPU node pools (labels per
the accelerator/topology contract, `google.com/tpu` in allocatable).
"""

from __future__ import annotations

import datetime

from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    INSTANCE_TYPE_LABEL,
    POOL_LABEL,
    SLICE_ID_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
)
from tpu_autoscaler.topology.shapes import CpuShape, SliceShape


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc).isoformat().replace("+00:00", "Z")


def tpu_host_payload(shape: SliceShape, slice_id: str, host_index: int,
                     created_at: float, *, pool: str | None = None,
                     ready: bool = True, preemptible: bool = False) -> dict:
    labels = {
        ACCELERATOR_LABEL: shape.accelerator_type,
        TOPOLOGY_LABEL: shape.topology_label,
        INSTANCE_TYPE_LABEL: shape.machine_type,
        SLICE_ID_LABEL: slice_id,
    }
    if pool:
        labels[POOL_LABEL] = pool
    if preemptible:
        labels["cloud.google.com/gke-spot"] = "true"
    return {
        "metadata": {
            "name": f"{slice_id}-h{host_index}",
            "labels": labels,
            "creationTimestamp": _iso(created_at),
        },
        # GKE stamps the TPU taint on every TPU node; workloads must carry
        # the matching toleration (deploy/example-v5e-64-jobset.yaml).
        "spec": {"taints": [{"key": TPU_RESOURCE, "value": "present",
                             "effect": "NoSchedule"}]},
        "status": {
            "allocatable": {
                "cpu": f"{shape.host_cpu_m}m",
                "memory": str(shape.host_memory),
                "pods": str(shape.host_pods),
                TPU_RESOURCE: str(shape.chips_per_host),
            },
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}],
        },
    }


def cpu_node_payload(shape: CpuShape, unit_id: str, created_at: float, *,
                     pool: str | None = None, ready: bool = True) -> dict:
    labels = {
        INSTANCE_TYPE_LABEL: shape.machine_type,
        SLICE_ID_LABEL: unit_id,
    }
    if pool:
        labels[POOL_LABEL] = pool
    return {
        "metadata": {
            "name": unit_id,
            "labels": labels,
            "creationTimestamp": _iso(created_at),
        },
        "spec": {},
        "status": {
            "allocatable": {
                "cpu": f"{shape.cpu_m}m",
                "memory": str(shape.memory),
                "pods": str(shape.pods),
            },
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}],
        },
    }
