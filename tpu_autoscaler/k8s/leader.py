"""Leader election over the Kubernetes Lease API.

The reference assumed a single replica (Deployment + crash-restart); with
leader election the autoscaler can run replicated for fast failover while
keeping exactly one writer.  Standard lease protocol (what client-go's
leaderelection does): acquire if the lease is absent, expired, or already
ours; renew by updating ``renewTime``; optimistic concurrency via
``resourceVersion`` so two candidates can't both win a transition — the
loser's conflicting update is rejected by the apiserver.

Crash-only safe: leadership is only ever claimed through the apiserver,
never remembered locally, and losing the lease simply makes the loop skip
its write phase until re-acquired.
"""

from __future__ import annotations

import datetime
import logging
import os
import socket
import uuid

log = logging.getLogger(__name__)

LEASE_NAME = "tpu-autoscaler"
LEASE_NAMESPACE = "kube-system"


def _rfc3339(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _parse(ts: str | None) -> float | None:
    if not ts:
        return None
    return datetime.datetime.fromisoformat(
        ts.replace("Z", "+00:00")).timestamp()


def default_identity() -> str:
    return f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


class LeaseLock:
    def __init__(self, client, *, name: str = LEASE_NAME,
                 namespace: str = LEASE_NAMESPACE,
                 identity: str | None = None,
                 lease_seconds: float = 15.0):
        self._client = client
        self._name = name
        self._namespace = namespace
        self.identity = identity or default_identity()
        self._ttl = lease_seconds

    def try_acquire(self, now: float) -> bool:
        """Acquire or renew; True iff we are the leader after this call."""
        try:
            lease = self._client.get_lease(self._namespace, self._name)
        except Exception:  # crash-only: apiserver unreachable means "not
            # leader" — the loop skips its write phase until re-acquired
            log.warning("lease read failed", exc_info=True)
            return False
        if lease is None:
            return self._write({"holderIdentity": self.identity,
                                "acquireTime": _rfc3339(now)}, None, now)
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        renew = _parse(spec.get("renewTime")) or _parse(
            spec.get("acquireTime")) or 0.0
        expired = now - renew > self._ttl
        if holder == self.identity or expired or not holder:
            merged = {**spec, "holderIdentity": self.identity}
            if holder != self.identity:
                merged["acquireTime"] = _rfc3339(now)
            return self._write(
                merged, lease.get("metadata", {}).get("resourceVersion"),
                now)
        return False

    def _write(self, spec: dict, resource_version: str | None,
               now: float) -> bool:
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self._name, "namespace": self._namespace},
            "spec": {**spec,
                     "renewTime": _rfc3339(now),
                     "leaseDurationSeconds": int(self._ttl)},
        }
        if resource_version is not None:
            body["metadata"]["resourceVersion"] = resource_version
        try:
            self._client.put_lease(self._namespace, self._name, body)
            return True
        except Exception:  # crash-only: losing the optimistic-concurrency
            # write IS the protocol outcome — the other candidate won
            log.info("lease write lost (conflict?)", exc_info=True)
            return False
