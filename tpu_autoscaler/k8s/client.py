"""Kubernetes API client interface + real HTTPS implementation.

The reference talked to the apiserver through pykube (kube.py, cluster.py);
this rebuild defines a narrow protocol so the control loop is written once
and runs against (a) the real apiserver over HTTPS and (b) the in-memory
fake (``tpu_autoscaler.k8s.fake``) that powers the e2e loop tests the
reference never had (SURVEY.md §5 "Implication for the rebuild").

The real client is deliberately dependency-light: the official ``kubernetes``
package is not assumed; ``requests`` + a bearer token / kubeconfig cover the
five verbs the autoscaler needs (list pods, list nodes, patch node, evict
pod, delete pod/node).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Protocol, runtime_checkable

log = logging.getLogger(__name__)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@runtime_checkable
class KubeClient(Protocol):
    """The five verbs the autoscaler needs from the apiserver."""

    def list_nodes(self) -> list[dict]: ...

    def list_pods(self) -> list[dict]: ...

    def patch_node(self, name: str, patch: dict) -> None: ...

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None: ...

    def evict_pod(self, namespace: str, name: str) -> None: ...

    def delete_pod(self, namespace: str, name: str) -> None: ...

    def delete_node(self, name: str) -> None: ...

    def get_lease(self, namespace: str, name: str) -> dict | None: ...

    def put_lease(self, namespace: str, name: str, body: dict) -> None: ...

    def create_event(self, namespace: str, body: dict) -> None: ...


class RestKubeClient:
    """Real apiserver client over HTTPS.

    Auth resolution order (reference parity: main.py supported kubeconfig
    or in-cluster service account):

    1. explicit ``base_url`` + ``token`` arguments,
    2. in-cluster service account (token + CA mounted at the standard path),
    3. ``$KUBERNETES_SERVICE_HOST`` env (in-cluster without mounts).
    """

    @classmethod
    def from_kubeconfig(cls, path: str, context: str | None = None,
                        dry_run: bool = False) -> "RestKubeClient":
        """Build a client from a kubeconfig file (reference parity:
        main.py --kubeconfig).  Supports token auth, client certificates,
        and inline base64 ``*-data`` fields (materialized to temp files)."""
        import base64
        import tempfile

        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f)

        def by_name(items, name):
            for item in items or []:
                if item.get("name") == name:
                    return item
            raise KeyError(f"{name!r} not found in kubeconfig")

        ctx_name = context or cfg.get("current-context")
        ctx = by_name(cfg.get("contexts"), ctx_name)["context"]
        cluster = by_name(cfg.get("clusters"), ctx["cluster"])["cluster"]
        user = by_name(cfg.get("users"), ctx["user"])["user"]

        def materialize(data_b64: str, suffix: str) -> str:
            f = tempfile.NamedTemporaryFile(delete=False, suffix=suffix)
            f.write(base64.b64decode(data_b64))
            f.close()
            return f.name

        ca: str | bool = True
        if cluster.get("certificate-authority"):
            ca = cluster["certificate-authority"]
        elif cluster.get("certificate-authority-data"):
            ca = materialize(cluster["certificate-authority-data"], ".crt")
        elif cluster.get("insecure-skip-tls-verify"):
            ca = False

        client = cls(base_url=cluster["server"], token=user.get("token"),
                     ca_cert=ca, dry_run=dry_run)
        cert = user.get("client-certificate") or (
            materialize(user["client-certificate-data"], ".crt")
            if user.get("client-certificate-data") else None)
        key = user.get("client-key") or (
            materialize(user["client-key-data"], ".key")
            if user.get("client-key-data") else None)
        if cert and key:
            client._session.cert = (cert, key)
        return client

    def __init__(self, base_url: str | None = None, token: str | None = None,
                 ca_cert: str | bool = True, dry_run: bool = False):
        import requests  # local import: tests never touch this class

        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "no apiserver endpoint: pass base_url or run in-cluster")
            base_url = f"https://{host}:{port}"
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            with open(f"{_SA_DIR}/token") as f:
                token = f.read().strip()
        if ca_cert is True and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_cert = f"{_SA_DIR}/ca.crt"
        self._base = base_url.rstrip("/")
        self._dry_run = dry_run
        self._session = requests.Session()
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        self._session.verify = ca_cert

    def _get(self, path: str) -> dict:
        r = self._session.get(f"{self._base}{path}", timeout=30)
        r.raise_for_status()
        return r.json()

    def _mutate(self, method: str, path: str, body: dict | None = None,
                content_type: str = "application/json") -> None:
        if self._dry_run:
            log.info("[dry-run] %s %s %s", method, path,
                     json.dumps(body) if body else "")
            return
        r = self._session.request(
            method, f"{self._base}{path}",
            data=json.dumps(body) if body is not None else None,
            headers={"Content-Type": content_type}, timeout=30)
        r.raise_for_status()

    def list_nodes(self) -> list[dict]:
        return self._get("/api/v1/nodes").get("items", [])

    def list_pods(self) -> list[dict]:
        return self._get("/api/v1/pods").get("items", [])

    def patch_node(self, name: str, patch: dict) -> None:
        self._mutate("PATCH", f"/api/v1/nodes/{name}", patch,
                     content_type="application/strategic-merge-patch+json")

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        self._mutate(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}", patch,
            content_type="application/strategic-merge-patch+json")

    def evict_pod(self, namespace: str, name: str) -> None:
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        self._mutate(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._mutate("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def delete_node(self, name: str) -> None:
        self._mutate("DELETE", f"/api/v1/nodes/{name}")

    def create_event(self, namespace: str, body: dict) -> None:
        self._mutate("POST", f"/api/v1/namespaces/{namespace}/events",
                     body)

    def get_lease(self, namespace: str, name: str) -> dict | None:
        import requests

        r = self._session.get(
            f"{self._base}/apis/coordination.k8s.io/v1/namespaces/"
            f"{namespace}/leases/{name}", timeout=10)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return r.json()

    def put_lease(self, namespace: str, name: str, body: dict) -> None:
        # Lease writes honor dry-run like every other mutation: a
        # --dry-run process must never acquire (steal) the production
        # leader Lease and halt real scaling.
        if self._dry_run:
            log.info("[dry-run] put lease %s/%s", namespace, name)
            return
        base = (f"{self._base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{namespace}/leases")
        exists = "resourceVersion" in body.get("metadata", {})
        import json as _json

        r = self._session.request(
            "PUT" if exists else "POST",
            f"{base}/{name}" if exists else base,
            data=_json.dumps(body),
            headers={"Content-Type": "application/json"}, timeout=10)
        r.raise_for_status()

    def watch_pods(self, timeout_seconds: int = 60,
                   resource_version: str | None = None):
        """Yield pod watch events (dicts) until the server closes the watch.

        Level-trigger upgrade over the reference's poll-sleep loop
        (main.py --sleep): the controller wakes the moment a pod changes
        instead of up to one poll period later.  Used via
        ``tpu_autoscaler.controller.watch.WatchTrigger``.

        ``resource_version`` resumes from a prior watch's cursor instead
        of replaying the world; bookmarks are requested so the cursor
        stays fresh across quiet periods.
        """
        import json as _json

        url = (f"{self._base}/api/v1/pods"
               f"?watch=1&timeoutSeconds={timeout_seconds}"
               f"&allowWatchBookmarks=true")
        if resource_version:
            url += f"&resourceVersion={resource_version}"
        r = self._session.get(url, stream=True,
                              timeout=timeout_seconds + 10)
        r.raise_for_status()
        for line in r.iter_lines():
            if line:
                yield _json.loads(line)
