"""Kubernetes API client interface + real HTTPS implementation.

The reference talked to the apiserver through pykube (kube.py, cluster.py);
this rebuild defines a narrow protocol so the control loop is written once
and runs against (a) the real apiserver over HTTPS and (b) the in-memory
fake (``tpu_autoscaler.k8s.fake``) that powers the e2e loop tests the
reference never had (SURVEY.md §5 "Implication for the rebuild").

The real client is deliberately dependency-light: the official ``kubernetes``
package is not assumed; ``requests`` + a bearer token / kubeconfig cover the
five verbs the autoscaler needs (list pods, list nodes, patch node, evict
pod, delete pod/node).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Protocol, runtime_checkable

log = logging.getLogger(__name__)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@runtime_checkable
class KubeClient(Protocol):
    """The five verbs the autoscaler needs from the apiserver."""

    def list_nodes(self) -> list[dict]: ...

    def list_pods(self) -> list[dict]: ...

    def patch_node(self, name: str, patch: dict) -> None: ...

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None: ...

    def evict_pod(self, namespace: str, name: str) -> None: ...

    def delete_pod(self, namespace: str, name: str) -> None: ...

    def delete_node(self, name: str) -> None: ...

    def get_lease(self, namespace: str, name: str) -> dict | None: ...

    def put_lease(self, namespace: str, name: str, body: dict) -> None: ...

    def create_event(self, namespace: str, body: dict) -> None: ...


class RestKubeClient:
    """Real apiserver client over HTTPS.

    Auth resolution order (reference parity: main.py supported kubeconfig
    or in-cluster service account):

    1. explicit ``base_url`` + ``token`` arguments,
    2. in-cluster service account (token + CA mounted at the standard path),
    3. ``$KUBERNETES_SERVICE_HOST`` env (in-cluster without mounts).
    """

    @classmethod
    def from_kubeconfig(cls, path: str, context: str | None = None,
                        dry_run: bool = False) -> "RestKubeClient":
        """Build a client from a kubeconfig file (reference parity:
        main.py --kubeconfig).  Supports token auth, client certificates,
        and inline base64 ``*-data`` fields (materialized to temp files)."""
        import base64
        import tempfile

        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f)

        def by_name(items, name):
            for item in items or []:
                if item.get("name") == name:
                    return item
            raise KeyError(f"{name!r} not found in kubeconfig")

        ctx_name = context or cfg.get("current-context")
        ctx = by_name(cfg.get("contexts"), ctx_name)["context"]
        cluster = by_name(cfg.get("clusters"), ctx["cluster"])["cluster"]
        user = by_name(cfg.get("users"), ctx["user"])["user"]

        def materialize(data_b64: str, suffix: str) -> str:
            f = tempfile.NamedTemporaryFile(delete=False, suffix=suffix)
            f.write(base64.b64decode(data_b64))
            f.close()
            return f.name

        ca: str | bool = True
        if cluster.get("certificate-authority"):
            ca = cluster["certificate-authority"]
        elif cluster.get("certificate-authority-data"):
            ca = materialize(cluster["certificate-authority-data"], ".crt")
        elif cluster.get("insecure-skip-tls-verify"):
            ca = False

        client = cls(base_url=cluster["server"], token=user.get("token"),
                     ca_cert=ca, dry_run=dry_run)
        cert = user.get("client-certificate") or (
            materialize(user["client-certificate-data"], ".crt")
            if user.get("client-certificate-data") else None)
        key = user.get("client-key") or (
            materialize(user["client-key-data"], ".key")
            if user.get("client-key-data") else None)
        if cert and key:
            client._session.cert = (cert, key)
        return client

    #: Transient apiserver responses worth retrying — rate limiting
    #: (429, the flooded-apiserver case that flaps leader election) and
    #: server-side hiccups.  Same scheme as actuators/gcp.py::GcpRest
    #: (bounded exponential backoff, full jitter, Retry-After honored),
    #: with a smaller budget: the reconcile loop re-runs every pass, so
    #: a verb only needs to survive a blip, not an outage.
    RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})
    max_attempts = 4
    backoff_base_s = 0.25
    backoff_cap_s = 4.0

    def __init__(self, base_url: str | None = None, token: str | None = None,
                 ca_cert: str | bool = True, dry_run: bool = False,
                 sleep=None, rng=None):
        import random
        import time as _time

        import requests  # local import: tests never touch this class

        self._sleep = sleep or _time.sleep
        self._rng = rng or random.Random()
        self._metrics = None
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "no apiserver endpoint: pass base_url or run in-cluster")
            base_url = f"https://{host}:{port}"
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            with open(f"{_SA_DIR}/token") as f:
                token = f.read().strip()
        if ca_cert is True and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_cert = f"{_SA_DIR}/ca.crt"
        self._base = base_url.rstrip("/")
        self._dry_run = dry_run
        self._session = requests.Session()
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        self._session.verify = ca_cert

    def set_metrics(self, metrics) -> None:
        """Wire the controller's metrics registry in (Controller calls
        this on construction) so ``kube_retries`` lands next to the
        actuators' ``rest_retries``."""
        self._metrics = metrics

    def _request_retrying(self, method: str, url: str, *, timeout: float,
                          retryable: frozenset | None = None,
                          max_attempts: int | None = None,
                          backoff_cap_s: float | None = None,
                          retry_after_cap_s: float | None = None,
                          **kw):
        """One apiserver request with bounded-backoff retries on
        connection errors and ``retryable`` statuses.  Returns the final
        Response (callers keep their own status handling — 404-is-None,
        409-is-conflict, raise_for_status — unchanged).

        Per-call overrides let the lease path shrink its budget below
        the lease TTL and the eviction path drop 429 (see callers).
        """
        import requests

        retryable = self.RETRYABLE_STATUSES if retryable is None \
            else retryable
        attempts = max_attempts or self.max_attempts
        attempt = 0
        while True:
            try:
                r = self._session.request(method, url, timeout=timeout,
                                          **kw)
            except requests.exceptions.RequestException as e:
                if attempt + 1 >= attempts:
                    raise
                self._note_retry(
                    f"connection error ({e.__class__.__name__})", url,
                    attempt, attempts)
                self._sleep(self._backoff_seconds(
                    attempt, None, backoff_cap_s, retry_after_cap_s))
                attempt += 1
                continue
            if r.status_code in retryable and attempt + 1 < attempts:
                self._note_retry(str(r.status_code), url, attempt,
                                 attempts)
                self._sleep(self._backoff_seconds(
                    attempt, r.headers.get("Retry-After"),
                    backoff_cap_s, retry_after_cap_s))
                attempt += 1
                continue
            return r

    def _backoff_seconds(self, attempt: int, retry_after,
                         cap_s: float | None = None,
                         retry_after_cap_s: float | None = None) -> float:
        from tpu_autoscaler.backoff import backoff_seconds

        cap = cap_s if cap_s is not None else self.backoff_cap_s
        return backoff_seconds(
            attempt, retry_after, base_s=self.backoff_base_s, cap_s=cap,
            retry_after_cap_s=(retry_after_cap_s if retry_after_cap_s
                               is not None else cap * 4),
            rng=self._rng)

    def _note_retry(self, why: str, url: str, attempt: int,
                    attempts: int) -> None:
        if self._metrics is not None:
            self._metrics.inc("kube_retries")
        log.warning("apiserver %s (attempt %d/%d) %s — retrying", why,
                    attempt + 1, attempts, url)

    def _get(self, path: str) -> dict:
        r = self._request_retrying("GET", f"{self._base}{path}",
                                   timeout=30)
        r.raise_for_status()
        return r.json()

    def _mutate(self, method: str, path: str, body: dict | None = None,
                content_type: str = "application/json",
                retryable: frozenset | None = None,
                ok_404: bool = False) -> None:
        if self._dry_run:
            log.info("[dry-run] %s %s %s", method, path,
                     json.dumps(body) if body else "")
            return
        r = self._request_retrying(
            method, f"{self._base}{path}",
            data=json.dumps(body) if body is not None else None,
            headers={"Content-Type": content_type}, timeout=30,
            retryable=retryable)
        if (ok_404 or method == "DELETE") and r.status_code == 404:
            # Idempotent teardown: a retried DELETE/eviction whose first
            # attempt committed (or a racing deleter) reports success,
            # not an exception that fails the reconcile step.
            log.debug("%s already gone (404)", path)
            return
        r.raise_for_status()

    def list_nodes(self) -> list[dict]:
        return self._get("/api/v1/nodes").get("items", [])

    def list_pods(self) -> list[dict]:
        return self._get("/api/v1/pods").get("items", [])

    # Raw list verbs: the full List response, not just items — the
    # informer needs the collection's metadata.resourceVersion as the
    # only safe point to resume a watch from after a LIST (k8s/
    # informer.py).  Same endpoints (and RBAC grants) as list_*.
    def list_nodes_raw(self) -> dict:
        return self._get("/api/v1/nodes")

    def list_pods_raw(self) -> dict:
        return self._get("/api/v1/pods")

    def patch_node(self, name: str, patch: dict) -> None:
        self._mutate("PATCH", f"/api/v1/nodes/{name}", patch,
                     content_type="application/strategic-merge-patch+json")

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        self._mutate(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}", patch,
            content_type="application/strategic-merge-patch+json")

    def evict_pod(self, namespace: str, name: str) -> None:
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        # 429 on THIS path is not a transient fault: the Eviction API
        # answers 429 when a PodDisruptionBudget disallows the
        # disruption — a policy verdict the drain loop must see now,
        # not after three backoffs that stall the whole reconcile pass.
        # 404 = already evicted/gone = success (retried POSTs whose
        # first attempt committed land here).
        self._mutate(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body, retryable=self.RETRYABLE_STATUSES - {429},
            ok_404=True)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._mutate("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def delete_node(self, name: str) -> None:
        self._mutate("DELETE", f"/api/v1/nodes/{name}")

    def create_event(self, namespace: str, body: dict) -> None:
        self._mutate("POST", f"/api/v1/namespaces/{namespace}/events",
                     body)

    # Lease verbs run on a TIGHT retry budget: the whole point is to
    # renew before the ~15 s lease TTL, so one blocked call must stay
    # well under it (2 attempts, <=1 s jitter, Retry-After clamped to
    # 2 s, 5 s socket timeout → worst case ~13 s; the default budget's
    # Retry-After cap alone exceeds the TTL).
    LEASE_ATTEMPTS = 2
    LEASE_BACKOFF_CAP_S = 1.0
    LEASE_RETRY_AFTER_CAP_S = 2.0
    LEASE_TIMEOUT_S = 5.0

    def get_lease(self, namespace: str, name: str) -> dict | None:
        # Retried (within the lease budget): a 429 on the lease READ
        # path must not make the current leader think it lost
        # (leader-election flap under a flooded apiserver).
        r = self._request_retrying(
            "GET",
            f"{self._base}/apis/coordination.k8s.io/v1/namespaces/"
            f"{namespace}/leases/{name}", timeout=self.LEASE_TIMEOUT_S,
            max_attempts=self.LEASE_ATTEMPTS,
            backoff_cap_s=self.LEASE_BACKOFF_CAP_S,
            retry_after_cap_s=self.LEASE_RETRY_AFTER_CAP_S)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return r.json()

    def put_lease(self, namespace: str, name: str, body: dict) -> None:
        # Lease writes honor dry-run like every other mutation: a
        # --dry-run process must never acquire (steal) the production
        # leader Lease and halt real scaling.
        if self._dry_run:
            log.info("[dry-run] put lease %s/%s", namespace, name)
            return
        base = (f"{self._base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{namespace}/leases")
        exists = "resourceVersion" in body.get("metadata", {})
        import json as _json

        # Retry-safe: the write is guarded by resourceVersion, so a
        # retry of an already-committed PUT surfaces as a 409 conflict
        # (NOT retried — losing the optimistic-concurrency race is a
        # leader-election outcome, not a transient fault; the next
        # try_acquire re-reads and recovers).
        r = self._request_retrying(
            "PUT" if exists else "POST",
            f"{base}/{name}" if exists else base,
            data=_json.dumps(body),
            headers={"Content-Type": "application/json"},
            timeout=self.LEASE_TIMEOUT_S,
            max_attempts=self.LEASE_ATTEMPTS,
            backoff_cap_s=self.LEASE_BACKOFF_CAP_S,
            retry_after_cap_s=self.LEASE_RETRY_AFTER_CAP_S)
        if r.status_code == 409 and not exists:
            # A retried CREATE whose first attempt committed (response
            # lost to a blip) answers 409 on the retry — the same
            # lost-response window the PUT path closes via
            # resourceVersion.  Re-read: if the lease's holder is the
            # identity we just wrote, the create succeeded and we ARE
            # the leader; failing here would make the winning candidate
            # skip a lease cycle (ADVICE r5 #3).  Any other holder is a
            # genuinely lost race, surfaced as the 409 below.
            current = self.get_lease(namespace, name) or {}
            ours = (body.get("spec") or {}).get("holderIdentity")
            if ours is not None and (current.get("spec") or {}).get(
                    "holderIdentity") == ours:
                log.info("lease %s/%s create raced its own retry; "
                         "holder is us — acquired", namespace, name)
                return
        r.raise_for_status()

    def watch_pods(self, timeout_seconds: int = 60,
                   resource_version: str | None = None):
        """Yield pod watch events (dicts) until the server closes the watch.

        Level-trigger upgrade over the reference's poll-sleep loop
        (main.py --sleep): the controller wakes the moment a pod changes
        instead of up to one poll period later.  Used via
        ``tpu_autoscaler.k8s.informer`` (and the older
        ``controller.watch.WatchTrigger``).

        ``resource_version`` resumes from a prior watch's cursor instead
        of replaying the world; bookmarks are requested so the cursor
        stays fresh across quiet periods.
        """
        return self._watch("/api/v1/pods", timeout_seconds,
                           resource_version)

    def watch_nodes(self, timeout_seconds: int = 60,
                    resource_version: str | None = None):
        """Yield node watch events — the informer's supply-side feed
        (slice hosts registering / going NotReady / being reclaimed)."""
        return self._watch("/api/v1/nodes", timeout_seconds,
                           resource_version)

    def _watch(self, path: str, timeout_seconds: int,
               resource_version: str | None):
        import json as _json

        url = (f"{self._base}{path}"
               f"?watch=1&timeoutSeconds={timeout_seconds}"
               f"&allowWatchBookmarks=true")
        if resource_version:
            url += f"&resourceVersion={resource_version}"
        r = self._session.get(url, stream=True,
                              timeout=timeout_seconds + 10)
        r.raise_for_status()
        for line in r.iter_lines():
            if line:
                yield _json.loads(line)
