"""Scheduler predicates beyond node-local admission.

Pod affinity / anti-affinity and topology spread are CLUSTER-state
predicates (they depend on where other pods sit), so they live here
rather than on ``Node.admits``.  Shared by the fake scheduler
(``FakeKube.schedule_step``) and the planner's CPU packing path — a
predicate modeled in only one of the two places either deadlocks pending
pods (planner thinks a pod fits a node the scheduler will refuse) or
over-provisions.

Scope, mirroring kube-scheduler's *filter* phase:

- ``requiredDuringSchedulingIgnoredDuringExecution`` pod affinity and
  anti-affinity (preferred/scoring terms are ignored);
- ``topologySpreadConstraints`` with ``whenUnsatisfiable: DoNotSchedule``
  (``ScheduleAnyway`` is scoring — ignored);
- label selectors support ``matchLabels`` and ``matchExpressions`` with
  In / NotIn / Exists / DoesNotExist;
- affinity terms default to the pod's own namespace; an explicit
  ``namespaces`` list is honored.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Protocol

HOSTNAME_KEY = "kubernetes.io/hostname"

_REQUIRED = "requiredDuringSchedulingIgnoredDuringExecution"


class NodeLike(Protocol):
    """What the predicates need from a node: identity + labels.  Real
    ``Node`` objects satisfy this; the planner also passes synthetic
    not-yet-existing nodes when simulating new capacity."""

    name: str
    labels: Mapping[str, str]


def topology_value(node: NodeLike, key: str) -> str | None:
    if key == HOSTNAME_KEY:
        # kubelet stamps hostname == node name; synthetic nodes may not
        # carry the label explicitly.
        return node.labels.get(key, node.name)
    return node.labels.get(key)


def label_selector_matches(selector: Mapping, labels: Mapping[str, str]
                           ) -> bool:
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key", "")
        op = expr.get("operator")
        values = expr.get("values") or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if labels.get(key) in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:  # unknown operator: conservatively no match
            return False
    return True


def has_scheduling_constraints(pod) -> bool:
    """Does this pod carry any HARD constraint these predicates model?"""
    aff = pod.affinity
    if (aff.get("podAffinity") or {}).get(_REQUIRED):
        return True
    if (aff.get("podAntiAffinity") or {}).get(_REQUIRED):
        return True
    return any(c.get("whenUnsatisfiable") == "DoNotSchedule"
               for c in pod.topology_spread)


def _term_namespaces(term: Mapping, pod_namespace: str) -> set[str]:
    ns = term.get("namespaces")
    return set(ns) if ns else {pod_namespace}


def _placed_matching(term: Mapping, pod_namespace: str,
                     placements: Mapping[str, list]) -> Iterable[str]:
    """Node names hosting a placed pod matched by the term's selector."""
    selector = term.get("labelSelector") or {}
    namespaces = _term_namespaces(term, pod_namespace)
    for node_name, placed in placements.items():
        for q in placed:
            if (q.namespace in namespaces
                    and label_selector_matches(selector, q.labels)):
                yield node_name
                break


def scheduling_blocks(pod, node: NodeLike,
                      placements: Mapping[str, list],
                      nodes_by_name: Mapping[str, NodeLike]) -> str | None:
    """Why ``pod`` cannot go on ``node`` given current ``placements``
    (bound pods AND tentative same-gang placements, keyed by node name),
    or None if the hard constraints allow it."""
    aff = pod.affinity

    for term in (aff.get("podAffinity") or {}).get(_REQUIRED, []):
        key = term.get("topologyKey", HOSTNAME_KEY)
        here = topology_value(node, key)
        if here is None:
            return f"affinity topologyKey {key} absent on node"
        matching = [m for m in _placed_matching(term, pod.namespace,
                                                placements)
                    if m in nodes_by_name]
        ok = any(topology_value(nodes_by_name[m], key) == here
                 for m in matching)
        if not ok and not matching:
            # kube-scheduler's bootstrap rule: a pod whose OWN labels
            # match the term may schedule when no matching pod exists
            # anywhere — otherwise the first replica of a self-affine
            # set could never start.
            selector = term.get("labelSelector") or {}
            ok = label_selector_matches(selector, pod.labels)
        if not ok:
            return (f"pod affinity: no matching pod in topology "
                    f"{key}={here}")

    for term in (aff.get("podAntiAffinity") or {}).get(_REQUIRED, []):
        key = term.get("topologyKey", HOSTNAME_KEY)
        here = topology_value(node, key)
        if here is None:
            continue  # node outside the topology: nothing to conflict with
        clash = any(
            topology_value(nodes_by_name[m], key) == here
            for m in _placed_matching(term, pod.namespace, placements)
            if m in nodes_by_name)
        if clash:
            return (f"pod anti-affinity: matching pod already in "
                    f"topology {key}={here}")

    for c in pod.topology_spread:
        if c.get("whenUnsatisfiable") != "DoNotSchedule":
            continue
        key = c.get("topologyKey", "")
        max_skew = int(c.get("maxSkew", 1))
        here = topology_value(node, key)
        if here is None:
            return f"topology spread key {key} absent on node"
        selector = c.get("labelSelector") or {}
        counts: dict[str, int] = {}
        for n in nodes_by_name.values():
            v = topology_value(n, key)
            if v is not None:
                counts.setdefault(v, 0)
        for node_name, placed in placements.items():
            n = nodes_by_name.get(node_name)
            v = topology_value(n, key) if n is not None else None
            if v is None:
                continue
            counts[v] = counts.get(v, 0) + sum(
                1 for q in placed
                if q.namespace == pod.namespace
                and label_selector_matches(selector, q.labels))
        floor = min(counts.values(), default=0)
        if counts.get(here, 0) + 1 - floor > max_skew:
            return (f"topology spread: placing in {key}={here} would "
                    f"skew {counts.get(here, 0) + 1 - floor} > "
                    f"maxSkew {max_skew}")

    return None
