"""In-memory fake apiserver + toy scheduler.

The reference had no e2e story at all — cloud and kube were mock.Mock() and
`--dry-run` was the manual integration path (SURVEY.md §5).  This fake
implements the same ``KubeClient`` protocol as the real REST client plus a
minimal kube-scheduler model (bind pending pods to fitting nodes), so the
whole control loop runs end-to-end in-process: pending pod → plan →
provision → nodes Ready → bind → Running.  That loop test is how the
north-star latency metric is exercised without a cluster.
"""

from __future__ import annotations

import copy
import itertools
import time

from tpu_autoscaler import concurrency
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.resources import ResourceVector

#: Watch journal bound: events older than this many mutations are
#: dropped; a watcher resuming from before the floor gets a 410 ERROR
#: event, exactly like a real apiserver whose etcd window expired.
_JOURNAL_MAX = 1000


class FakeKube:
    """Fake apiserver: payload-dict store implementing KubeClient.

    Since ISSUE 2 it also models the two apiserver mechanisms the
    informer (k8s/informer.py) is built on: every mutation bumps the
    object's ``metadata.resourceVersion`` from one global sequence, and
    ``watch_pods``/``watch_nodes`` stream ADDED/MODIFIED/DELETED events
    from a bounded journal (Condition-signalled, so watch threads block
    instead of spinning; resuming below the journal floor yields a 410
    ERROR event).  Journaling only engages once the first watch is
    opened — the pure poll-mode tests and the north-star overhead bench
    pay one integer bump per mutation, nothing more.
    """

    def __init__(self):
        self._nodes: dict[str, dict] = {}
        self._pods: dict[tuple[str, str], dict] = {}
        self._leases: dict[tuple[str, str], dict] = {}
        self._uid = itertools.count(1)
        self.verb_log: list[tuple] = []
        self.events: list[tuple[str, dict]] = []
        # (namespace, name) pairs whose eviction is blocked directly
        # (tests), plus declarative PodDisruptionBudgets (add_pdb).
        self.pdb_protected: set[tuple[str, str]] = set()
        self._pdbs: list[dict] = []
        # Watch machinery: one global resourceVersion sequence, a
        # bounded (seq, kind, type, payload-copy) journal, and a
        # Condition watchers block on.  _journaling stays False (and the
        # floor tracks the head) until the first watch_* call, so
        # journal copies cost nothing in poll-only use.
        self._watch_cond = concurrency.Condition()
        self._last_seq = 0
        self._journal: list[tuple[int, str, str, dict]] = []
        self._journal_floor = 0
        self._journaling = False

    # ---- resourceVersion + watch journal -------------------------------

    def _note_change(self, kind: str, payload: dict, etype: str) -> None:
        """Record one mutation: bump the object's resourceVersion and,
        when a watcher exists, append a snapshot to the journal.

        The whole mutation happens under ``_watch_cond``: e2e tests
        drive the fake from the controller thread AND the test thread,
        so an unguarded ``_last_seq += 1`` could mint duplicate seqs —
        breaking the ``seq > cursor`` resume invariant watchers rely on.
        """
        with self._watch_cond:
            self._last_seq += 1
            seq = self._last_seq
            payload.setdefault("metadata", {})["resourceVersion"] = str(seq)
            if not self._journaling:
                self._journal_floor = seq
                return
            self._journal.append((seq, kind, etype,
                                  copy.deepcopy(payload)))
            if len(self._journal) > _JOURNAL_MAX:
                dropped = self._journal[:-_JOURNAL_MAX]
                self._journal = self._journal[-_JOURNAL_MAX:]
                self._journal_floor = dropped[-1][0]
            self._watch_cond.notify_all()

    def watch_pods(self, timeout_seconds: int = 60,
                   resource_version: str | None = None):
        # Journaling engages at CALL time (like the HTTP request
        # opening), not first iteration — mutations between opening the
        # watch and consuming it must not be lost.
        with self._watch_cond:
            self._journaling = True
        return self._watch("pods", timeout_seconds, resource_version)

    def watch_nodes(self, timeout_seconds: int = 60,
                    resource_version: str | None = None):
        with self._watch_cond:
            self._journaling = True
        return self._watch("nodes", timeout_seconds, resource_version)

    def _watch(self, kind: str, timeout_seconds: int,
               resource_version: str | None):
        """Stream journal events with seq > resource_version until the
        window closes (generator return = server-side close).  No
        resource_version means "from now" — the informer always relists
        first and passes the list's resourceVersion."""
        deadline = time.monotonic() + timeout_seconds
        with self._watch_cond:
            cursor = (int(resource_version) if resource_version
                      else self._last_seq)
        while True:
            with self._watch_cond:
                # Floor check EVERY round, not just at open: the journal
                # can trim past a slow consumer's cursor mid-stream, and
                # silently skipping the dropped events would hand the
                # watcher a gapped view with no signal to relist.  (All
                # yields happen outside the lock — a generator suspended
                # mid-`with` would hold it across consumer code.)
                gone = cursor < self._journal_floor
                batch = [] if gone else [e for e in self._journal
                                         if e[0] > cursor and e[1] == kind]
                if not gone and not batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._watch_cond.wait(min(remaining, 0.05))
                    continue
            if gone:
                yield {"type": "ERROR",
                       "object": {"code": 410,
                                  "message": "too old resource version"}}
                return
            for seq, _kind, etype, payload in batch:
                cursor = seq
                yield {"type": etype, "object": payload}

    # ---- KubeClient protocol -------------------------------------------

    def list_nodes(self) -> list[dict]:
        return list(self._nodes.values())

    def list_pods(self) -> list[dict]:
        return list(self._pods.values())

    def list_nodes_raw(self) -> dict:
        return {"metadata": {"resourceVersion": str(self._last_seq)},
                "items": list(self._nodes.values())}

    def list_pods_raw(self) -> dict:
        return {"metadata": {"resourceVersion": str(self._last_seq)},
                "items": list(self._pods.values())}

    def patch_node(self, name: str, patch: dict) -> None:
        self.verb_log.append(("patch_node", name, patch))
        node = self._nodes[name]
        spec = patch.get("spec") or {}
        if "unschedulable" in spec:
            node.setdefault("spec", {})["unschedulable"] = \
                spec["unschedulable"]
        self._merge_meta(node, patch)
        self._note_change("nodes", node, "MODIFIED")

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        self.verb_log.append(("patch_pod", namespace, name, patch))
        pod = self._pods[(namespace, name)]
        self._merge_meta(pod, patch)
        self._note_change("pods", pod, "MODIFIED")

    @staticmethod
    def _merge_meta(obj: dict, patch: dict) -> None:
        # Strategic-merge semantics for metadata maps: null deletes a key.
        meta = patch.get("metadata") or {}
        for key in ("annotations", "labels"):
            if key in meta:
                target = obj.setdefault("metadata", {}).setdefault(key, {})
                for k, v in meta[key].items():
                    if v is None:
                        target.pop(k, None)
                    else:
                        target[k] = v

    def evict_pod(self, namespace: str, name: str) -> None:
        self.verb_log.append(("evict", namespace, name))
        if (namespace, name) in self.pdb_protected \
                or self._pdb_blocks(namespace, name):
            # Model the eviction API's 429 when a PodDisruptionBudget
            # blocks the disruption.
            raise RuntimeError("429: Cannot evict pod as it would violate "
                               "the pod's disruption budget.")
        gone = self._pods.pop((namespace, name), None)
        if gone is not None:
            self._note_change("pods", gone, "DELETED")

    def _pdb_blocks(self, namespace: str, name: str) -> bool:
        """Would evicting this pod violate a PodDisruptionBudget?

        minAvailable semantics: the disruption is allowed only if the
        healthy matching count AFTER the eviction stays >= minAvailable —
        and per the real API's IfHealthyBudget default, evicting an
        UNHEALTHY pod doesn't reduce the healthy count, so it is allowed
        whenever the budget is currently met.
        """
        import math

        pod = self._pods.get((namespace, name))
        if pod is None:
            return False
        pod_labels = pod.get("metadata", {}).get("labels") or {}
        target_healthy = pod.get("status", {}).get("phase") == "Running"
        for pdb in self._pdbs:
            if pdb.get("metadata", {}).get("namespace",
                                           "default") != namespace:
                continue
            selector = (pdb.get("spec", {}).get("selector", {})
                        .get("matchLabels") or {})
            if not all(pod_labels.get(k) == v
                       for k, v in selector.items()):
                continue
            matching = [
                p for (ns, _), p in self._pods.items()
                if ns == namespace
                and all((p.get("metadata", {}).get("labels") or {})
                        .get(k) == v for k, v in selector.items())]
            healthy = sum(1 for p in matching
                          if p.get("status", {}).get("phase") == "Running")
            raw = pdb["spec"]["minAvailable"]
            if isinstance(raw, str) and raw.endswith("%"):
                # Percent of the registered expected-pod base (real k8s
                # derives it from the owning controller's replicas; the
                # fake takes it explicitly so the budget can't ratchet
                # down as pods are evicted).
                min_available = math.ceil(
                    pdb["_expected_pods"] * int(raw[:-1]) / 100)
            else:
                min_available = int(raw)
            after = healthy - (1 if target_healthy else 0)
            if after < min_available:
                return True
        return False

    def add_pdb(self, payload: dict,
                expected_pods: int | None = None) -> None:
        """Register a PodDisruptionBudget.

        Supported subset, rejected loudly outside it: spec.selector.
        matchLabels (non-empty, no matchExpressions) + spec.minAvailable
        ONLY (int >= 0, or "N%" with ``expected_pods`` given as the
        percentage base — real k8s derives that base from the owning
        controller's replica count, which a fake apiserver cannot know).
        """
        import re

        spec = payload.get("spec") or {}
        unsupported = sorted(set(spec) - {"minAvailable", "selector"})
        if "minAvailable" not in spec or unsupported:
            raise ValueError(
                "fake PDB supports only minAvailable+selector (got: "
                f"{sorted(spec)})")
        selector = spec.get("selector") or {}
        if not selector.get("matchLabels") \
                or set(selector) - {"matchLabels"}:
            raise ValueError(
                "fake PDB requires a non-empty selector.matchLabels "
                "(and nothing else, e.g. no matchExpressions)")
        raw = spec["minAvailable"]
        if isinstance(raw, str):
            if not re.fullmatch(r"\d+%", raw):
                raise ValueError(
                    f"bad minAvailable {raw!r}: expected int or 'N%'")
            if expected_pods is None:
                raise ValueError(
                    "percentage minAvailable needs expected_pods (the "
                    "replica base real k8s gets from the controller)")
            payload = {**payload, "_expected_pods": expected_pods}
        elif not isinstance(raw, int) or raw < 0:
            raise ValueError(
                f"bad minAvailable {raw!r}: expected int >= 0 or 'N%'")
        self._pdbs.append(payload)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.verb_log.append(("delete_pod", namespace, name))
        gone = self._pods.pop((namespace, name), None)
        if gone is not None:
            self._note_change("pods", gone, "DELETED")

    def delete_node(self, name: str) -> None:
        self.verb_log.append(("delete_node", name))
        gone = self._nodes.pop(name, None)
        if gone is not None:
            self._note_change("nodes", gone, "DELETED")

    def create_event(self, namespace: str, body: dict) -> None:
        self.events.append((namespace, body))

    def get_lease(self, namespace: str, name: str) -> dict | None:
        import copy

        lease = self._leases.get((namespace, name))
        return copy.deepcopy(lease) if lease else None

    def put_lease(self, namespace: str, name: str, body: dict) -> None:
        """Optimistic concurrency like the real apiserver: an update whose
        resourceVersion doesn't match (or a create over an existing lease)
        raises — the losing candidate's write is rejected."""
        import copy

        key = (namespace, name)
        existing = self._leases.get(key)
        rv = body.get("metadata", {}).get("resourceVersion")
        if existing is None:
            if rv is not None:
                raise RuntimeError("409: lease vanished")
            stored = copy.deepcopy(body)
            stored["metadata"]["resourceVersion"] = "1"
            self._leases[key] = stored
            return
        current_rv = existing["metadata"]["resourceVersion"]
        if rv is None or rv != current_rv:
            raise RuntimeError(
                f"409: resourceVersion conflict ({rv} != {current_rv})")
        stored = copy.deepcopy(body)
        stored["metadata"]["resourceVersion"] = str(int(current_rv) + 1)
        self._leases[key] = stored

    # ---- fixture mutators ----------------------------------------------

    def add_node(self, payload: dict) -> None:
        payload.setdefault("metadata", {}).setdefault(
            "uid", f"fake-{next(self._uid)}")
        self._nodes[payload["metadata"]["name"]] = payload
        self._note_change("nodes", payload, "ADDED")

    def add_pod(self, payload: dict) -> None:
        meta = payload.setdefault("metadata", {})
        meta.setdefault("uid", f"fake-{next(self._uid)}")
        self._pods[(meta.get("namespace", "default"), meta["name"])] = payload
        self._note_change("pods", payload, "ADDED")

    def get_pod(self, namespace: str, name: str) -> dict | None:
        return self._pods.get((namespace, name))

    def taint_node(self, name: str, key: str,
                   effect: str = "NoSchedule") -> None:
        """Apply one taint to a node (idempotent) — how the fault tier
        models GKE's impending-termination / spot-reclaim notices."""
        node = self._nodes[name]
        taints = node.setdefault("spec", {}).setdefault("taints", [])
        if not any(t.get("key") == key for t in taints):
            taints.append({"key": key, "effect": effect})
            self._note_change("nodes", node, "MODIFIED")

    def expire_watch_window(self) -> None:
        """Advance the watch journal floor to the head, modeling an etcd
        compaction: every watcher holding an older cursor gets a 410 on
        its next read and must relist.  The chaos tier's 410 flood uses
        this instead of thousands of synthetic mutations."""
        with self._watch_cond:
            self._journal_floor = self._last_seq
            self._journal = []
            self._watch_cond.notify_all()

    def set_node_ready(self, name: str, ready: bool) -> None:
        node = self._nodes[name]
        conds = node["status"].setdefault("conditions", [])
        for c in conds:
            if c.get("type") == "Ready":
                c["status"] = "True" if ready else "False"
                break
        else:
            conds.append({"type": "Ready",
                          "status": "True" if ready else "False"})
        self._note_change("nodes", node, "MODIFIED")

    # ---- toy kube-scheduler --------------------------------------------

    def schedule_step(self) -> int:
        """One scheduling pass: bind pending pods GANG-atomically.

        Models kube-scheduler + gang admission (JobSet/kueue semantics):
        a gang's pods bind only if EVERY member fits simultaneously —
        partial binding would misrepresent exactly the failure mode the
        autoscaler exists to prevent.  Solo pods are singleton gangs.
        Unbindable pods get/keep the Unschedulable condition, the demand
        signal the autoscaler reads.  Returns pods bound this pass.
        """
        from tpu_autoscaler.k8s.gangs import group_into_gangs
        from tpu_autoscaler.k8s.scheduling import scheduling_blocks

        nodes = [Node(p) for p in self._nodes.values()]
        nodes_by_name = {n.name: n for n in nodes}
        pods = [Pod(p) for p in self._pods.values()]
        free: dict[str, ResourceVector] = {}
        for n in nodes:
            if n.is_ready and not n.unschedulable:
                free[n.name] = n.allocatable
        # Placements (bound pods, then tentative same-pass bindings) feed
        # the affinity/anti-affinity/spread predicates.
        placed_by_node: dict[str, list[Pod]] = {}
        for p in pods:
            # Terminated pods neither hold resources nor count for the
            # affinity/spread predicates (kube-scheduler semantics; also
            # keeps this aligned with the planner's placement view).
            if p.node_name and p.phase in {"Pending", "Running"}:
                placed_by_node.setdefault(p.node_name, []).append(p)
                if p.node_name in free:
                    free[p.node_name] = free[p.node_name] - p.resources

        bound = 0
        pending = [p for p in pods
                   if not p.node_name and p.phase == "Pending"]

        def try_place(gang, allowed, trial, trial_placed):
            placements: list[tuple[Pod, str]] = []
            for p in gang.pods:
                target = next(
                    (n for n in allowed
                     if n.name in trial and n.admits(p)
                     and p.resources.fits_in(trial[n.name])
                     and scheduling_blocks(p, n, trial_placed,
                                           nodes_by_name) is None), None)
                if target is None:
                    return None
                trial[target.name] = trial[target.name] - p.resources
                trial_placed.setdefault(target.name, []).append(p)
                placements.append((p, target.name))
            return placements

        for gang in group_into_gangs(pending):
            # Tentative placement for the WHOLE gang against a copy.
            trial = dict(free)
            trial_placed = {k: list(v) for k, v in placed_by_node.items()}
            if gang.requests_tpu:
                # Slice-atomic placement (GKE TPU semantics): every
                # member lands within ONE slice — first-fit per pod
                # could interleave two same-shape gangs across two
                # identical slices, bisecting both ICI domains (the
                # chaos tier's gang-integrity invariant caught exactly
                # that).  Slices already hosting this gang's pods are
                # preferred, modeling topology-aware scheduling.
                by_slice: dict[str, list[Node]] = {}
                for n in nodes:
                    if n.is_tpu and n.slice_id:
                        by_slice.setdefault(n.slice_id, []).append(n)
                mine = {n.slice_id for n in nodes
                        if n.is_tpu and n.slice_id
                        for q in placed_by_node.get(n.name, [])
                        if q.gang_key == gang.key}
                # Gang/ICI-aware candidates (GKE multi-host TPU
                # semantics).  (1) A MULTI-host slice is exclusively
                # scheduled: a gang's pods never land beside another
                # gang's workload — chaos-found at repair seed 85,
                # where a recreated lone member bound beside a foreign
                # gang on a half-free slice its siblings could never
                # follow it onto, converging the gang split.  (2) A
                # gang already holding slice(s) binds ONLY within
                # them: topology affinity pins a job to its slice, so
                # a stray member must wait for its siblings (or the
                # repair replacement) instead of splitting the ICI
                # domain onto fresh capacity.  Single-host slices stay
                # shareable supply.
                foreign = set()
                for sid, members in by_slice.items():
                    if len(members) <= 1:
                        continue
                    for n in members:
                        if any(q.is_workload and q.gang_key != gang.key
                               for q in placed_by_node.get(n.name, [])):
                            foreign.add(sid)
                            break
                # _gang_exclusive is True except under the promoted
                # chaos fixture's sabotage hook, which re-opens the
                # pre-fix first-fit semantics (testing only).
                if not getattr(self, "_gang_exclusive", True):
                    candidates = list(by_slice)
                elif mine:
                    candidates = [s for s in by_slice if s in mine]
                else:
                    candidates = [s for s in by_slice
                                  if s not in foreign]
                placements = None
                for sid in sorted(candidates,
                                  key=lambda s: (s not in mine, s)):
                    trial = dict(free)
                    trial_placed = {k: list(v)
                                    for k, v in placed_by_node.items()}
                    placements = try_place(gang, by_slice[sid], trial,
                                           trial_placed)
                    if placements is not None:
                        break
            else:
                placements = try_place(gang, nodes, trial, trial_placed)
            if placements is None:
                for p in gang.pods:
                    payload = self._pods[(p.namespace, p.name)]
                    conds = payload["status"].setdefault("conditions", [])
                    if not any(c.get("type") == "PodScheduled"
                               for c in conds):
                        conds.append({"type": "PodScheduled",
                                      "status": "False",
                                      "reason": "Unschedulable"})
                        self._note_change("pods", payload, "MODIFIED")
                continue
            free = trial
            placed_by_node = trial_placed
            for p, node_name in placements:
                payload = self._pods[(p.namespace, p.name)]
                payload["spec"]["nodeName"] = node_name
                payload["status"]["phase"] = "Running"
                payload["status"]["conditions"] = [
                    {"type": "PodScheduled", "status": "True"}]
                self._note_change("pods", payload, "MODIFIED")
                bound += 1
        return bound
