"""Gang detection: group pending pods into atomic demand units.

The reference scaled per-pod (cluster.py §Cluster.scale: first-fit each
pending pod into a pool).  For TPUs that is wrong: a multi-host JAX job is a
*gang* — N pods that must all land on one ICI slice simultaneously, so the
demand unit presented to the fit engine is the gang, not the pod
(SURVEY.md §6.7, §8.2).  One Kubernetes Job == one gang == one slice; a
JobSet with replicated jobs is one gang per slice (multi-slice over DCN,
BASELINE config #4).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterable

from tpu_autoscaler.k8s.objects import Pod
from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.topology.catalog import TPU_RESOURCE


@dataclasses.dataclass
class Gang:
    """An atomic demand unit: one or more pods that schedule together."""

    key: tuple[str, str, str]
    pods: list[Pod]

    @property
    def size(self) -> int:
        return len(self.pods)

    @property
    def namespace(self) -> str:
        return self.key[1]

    @property
    def name(self) -> str:
        return self.key[2]

    @functools.cached_property
    def total_resources(self) -> ResourceVector:
        # Cached: gangs are rebuilt from pods every reconcile pass, so
        # the aggregate can never go stale, and the fit engine reads
        # these properties O(shapes) times per gang.
        total = ResourceVector()
        for p in self.pods:
            total = total + p.resources
        return total

    @functools.cached_property
    def per_pod_resources(self) -> ResourceVector:
        """Request of one member pod (gang members are homogeneous; if they
        are not, the max per axis is the safe envelope).  Cached (see
        total_resources)."""
        envelope: dict[str, float] = {}
        for p in self.pods:
            for k, v in p.resources.as_dict().items():
                envelope[k] = max(envelope.get(k, 0.0), v)
        return ResourceVector(envelope)

    @property
    def tpu_chips(self) -> int:
        """Total chips the gang demands across all its pods."""
        return int(self.total_resources.get(TPU_RESOURCE))

    @property
    def requests_tpu(self) -> bool:
        return self.tpu_chips > 0

    @property
    def node_selectors(self) -> dict[str, str]:
        """Merged nodeSelector across members.

        Members of a real gang share a pod template so selectors agree; on
        conflict the union is taken (a node must satisfy all), which can only
        make the fit more conservative.
        """
        merged: dict[str, str] = {}
        for p in self.pods:
            merged.update(p.node_selectors)
        return merged

    @property
    def jobset_name(self) -> str | None:
        return self.pods[0].jobset_name if self.pods else None

    @property
    def multislice_group_key(self) -> tuple[str, str, str] | None:
        """Identity of the multislice (DCN) group this gang belongs to.

        A JobSet's replicated jobs are one gang per slice; when several of
        them are pending together the planner provisions them as ONE
        multislice unit (a single QueuedResource with node_count=N, the
        XPK provisioning model) so Cloud TPU co-schedules the slices.
        ``None`` for gangs outside any JobSet.
        """
        js = self.jobset_name
        return ("jobset", self.namespace, js) if js else None

    @property
    def oldest_created(self):
        times = [p.created for p in self.pods if p.created is not None]
        return min(times) if times else None

    @property
    def priority(self) -> int:
        """Scheduling priority from spec.priority (admission-resolved
        priorityClassName); gang priority = max over members."""
        return max((p.priority for p in self.pods), default=0)

    def __repr__(self) -> str:
        return (f"Gang({self.key}, pods={self.size}, "
                f"chips={self.tpu_chips})")


def group_into_gangs(pods: Iterable[Pod]) -> list[Gang]:
    """Group pods into gangs by gang_key, highest priority then oldest.

    Ordering matters for fairness under capacity clamps: the reference
    served pods in list order (cluster.py §Cluster.scale); here demand is
    served by (priority desc, age asc) at gang granularity, so
    high-priority jobs win contended chip budget and equal-priority jobs
    queue fairly.
    """
    by_key: dict[tuple[str, str, str], list[Pod]] = {}
    for pod in pods:
        by_key.setdefault(pod.gang_key, []).append(pod)
    gangs = [Gang(key=k, pods=v) for k, v in by_key.items()]
    # Within a priority tier: gangs with no timestamp sort last; ties
    # break by key for determinism.
    gangs.sort(key=lambda g: (
        -g.priority,
        (g.oldest_created is None),
        g.oldest_created.timestamp() if g.oldest_created else 0.0,
        g.key))
    return gangs
