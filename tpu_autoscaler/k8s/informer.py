"""Watch-fed object store: the cached observe path for the reconciler.

``Controller.reconcile_once`` used to re-LIST and re-parse every node and
pod from the apiserver on every pass.  At 60 s polls that was noise; the
watch-triggered loop (controller/watch.py) wakes near-instantly, so at
production scale (thousands of pods, 5 s fallback interval) state
collection dominated both controller CPU and apiserver load.  This
module generalizes ``WatchTrigger`` into an informer, the standard
client-go shape:

- one background thread per resource (pods, and nodes when the client
  supports ``watch_nodes``) holds a watch open and applies
  ADDED/MODIFIED/DELETED deltas to a lock-guarded ``ObjectCache``;
- parsing happens once per delta via the (uid, resourceVersion)-memoized
  ``parse_pod``/``parse_node`` (k8s/objects.py), so an unchanged object
  is never re-parsed — a snapshot is an O(n) list copy of already-parsed
  objects;
- a 410 Gone (expired cursor) or any watch failure marks the cache
  unsynced; the watch thread relists (full LIST, counted in
  ``informer_relists``) and resumes watching from the list's
  resourceVersion.  Crash-only: the cache is a pure optimization — a
  cold start, a watch gap, or a crashed thread just mean the next read
  falls back to a direct LIST (``informer_fallback_lists``) until the
  watch re-syncs;
- relevant deltas set the reconcile loop's wake Event, subsuming
  WatchTrigger's level-trigger role.

Consistency model (docs/INFORMER.md): snapshots are immutable lists of
read-only objects, internally consistent as of the cache's cursor, and
at most one watch-delivery behind the apiserver when healthy.  The two
places the reconciler must not act on a stale view — supply that just
went ACTIVE, and a drain cancelled mid-pass — bypass the cache with a
direct LIST (reconciler.py ``_fresh_nodes``).

Thread discipline (TAT2xx, TAR5xx): the watch thread shares state with
readers only through ``ObjectCache`` (every mutation under its Lock),
the wake ``threading.Event``, and the stop Event.  All primitives come
from the ``concurrency`` seam so the deterministic-schedule harness
(testing/sched.py) can drive these exact code paths.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping

from tpu_autoscaler import concurrency
from tpu_autoscaler.backoff import watch_backoff_seconds

if TYPE_CHECKING:
    from tpu_autoscaler.metrics import Metrics

log = logging.getLogger(__name__)

#: Delta types that change reconcile-relevant state (BOOKMARK and ERROR
#: events move the cursor / signal failure but carry no state change).
RELEVANT_TYPES = frozenset({"ADDED", "MODIFIED", "DELETED"})


class WatchGone(RuntimeError):
    """410 Gone: the watch cursor expired; a full relist is required."""


class WatchError(RuntimeError):
    """An ERROR event on an otherwise-open stream (non-410)."""


class ObjectCache:
    """Lock-guarded store of one resource's payloads + parsed objects.

    Written only by its resource's watch thread (replace/apply/
    mark_unsynced); read by the reconcile thread (snapshot).  ``synced``
    is False until the first successful relist and again after any
    watch failure — readers fall back to a direct LIST while unsynced.
    """

    def __init__(self, kind: str,
                 parse: Callable[[Mapping[str, Any]], Any]) -> None:
        self.kind = kind
        self._parse = parse
        self._lock = concurrency.Lock()
        self._objects: dict[str, dict] = {}
        self._parsed: dict[str, Any] = {}
        self._resource_version: str | None = None
        self._synced = False

    @staticmethod
    def _key(obj: Mapping[str, Any]) -> str | None:
        meta = obj.get("metadata") or {}
        return meta.get("uid") or meta.get("name") or None

    @property
    def synced(self) -> bool:
        with self._lock:
            return self._synced

    @property
    def resource_version(self) -> str | None:
        with self._lock:
            return self._resource_version

    def replace(self, items: Iterable[dict],
                resource_version: str | None) -> None:
        """Install a full LIST result (relist / initial sync)."""
        objects: dict[str, dict] = {}
        parsed: dict[str, Any] = {}
        for item in items:
            key = self._key(item)
            if key is None:
                continue
            objects[key] = item
            # Memoized on (uid, resourceVersion): a relist re-parses
            # only objects that actually changed since last seen.
            parsed[key] = self._parse(item)
        with self._lock:
            self._objects = objects
            self._parsed = parsed
            self._resource_version = resource_version
            self._synced = True

    def apply(self, event: Mapping[str, Any]) -> bool:
        """Apply one watch event; True iff it changed relevant state.

        Raises ``WatchGone`` on a 410 ERROR event (cursor expired, the
        caller must relist) and ``WatchError`` on any other ERROR.
        """
        etype = event.get("type")
        obj = event.get("object") or {}
        if etype == "ERROR":
            if obj.get("code") == 410:
                raise WatchGone(str(obj.get("message", "410 Gone")))
            raise WatchError(str(obj.get("message", "watch ERROR event")))
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        key = self._key(obj)
        if etype in ("ADDED", "MODIFIED") and key is not None:
            parsed = self._parse(obj)
            with self._lock:
                self._objects[key] = dict(obj)
                self._parsed[key] = parsed
                if rv:
                    self._resource_version = rv
            return True
        with self._lock:
            if etype == "DELETED" and key is not None:
                self._objects.pop(key, None)
                self._parsed.pop(key, None)
            if rv:
                # BOOKMARK (and DELETED) keep the cursor fresh.
                self._resource_version = rv
        return etype in RELEVANT_TYPES

    def mark_unsynced(self) -> None:
        """Watch failed or gapped: serve LIST fallbacks until relisted.

        Keeps the cached content (the next relist reuses its parsed
        objects through the memo) but drops the cursor — resuming a
        possibly-gapped watch from the old cursor could miss deltas.
        """
        with self._lock:
            self._synced = False
            self._resource_version = None

    def snapshot(self) -> list[Any] | None:
        """Parsed objects as an immutable-by-convention list, or None
        when unsynced (caller falls back to a direct LIST)."""
        with self._lock:
            if not self._synced:
                return None
            return list(self._parsed.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class ResourceWatch(concurrency.Thread):
    """One resource's relist+watch loop, feeding its ObjectCache.

    Failure semantics match WatchTrigger (VERDICT r1 item 6): bounded
    exponential backoff with full jitter, ``watch_failures`` counted,
    only the first failure of a streak logged at WARNING.  On top of
    that: every failure (and every 410) marks the cache unsynced so the
    next loop iteration relists before re-watching — counted in
    ``informer_relists``.
    """

    def __init__(self, cache: ObjectCache,
                 list_fn: Callable[[], tuple[list[dict], str | None]],
                 watch_fn: Callable[..., Iterable[Mapping[str, Any]]],
                 wake: threading.Event | None = None,
                 timeout_seconds: int = 60,
                 resync_seconds: float = 900.0,
                 metrics: "Metrics | None" = None,
                 rng: random.Random | None = None,
                 tracer=None):
        super().__init__(daemon=True, name=f"{cache.kind}-informer")
        self._cache = cache
        self._list = list_fn
        self._watch = watch_fn
        self._wake = wake
        self._timeout = timeout_seconds
        self._resync_seconds = resync_seconds
        self._stopped = concurrency.Event()
        self._metrics = metrics
        # Tracer (obs/trace.py): relists and failures are span-worthy
        # (rare, and exactly what a slow-detection investigation needs);
        # per-event deltas are NOT traced — the hot path stays hot.
        self._tracer = tracer
        self._rng = rng or random.Random()
        self._failure_streak = 0
        self._last_relist_mono: float | None = None

    def stop(self) -> None:
        self._stopped.set()

    # -- internals, factored for testability (all thread-owned) ----------

    def _backoff_seconds(self) -> float:
        return watch_backoff_seconds(self._failure_streak, self._rng)

    def _inc(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.inc(name)

    def _relist(self) -> None:
        from tpu_autoscaler.obs import maybe_span

        with maybe_span(self._tracer, "informer.relist",
                        attrs={"kind": self._cache.kind}) as span:
            items, rv = self._list()
            if span is not None:
                # Via the tracer lock: /debugz may be copying this
                # still-open span concurrently.
                self._tracer.annotate(span, objects=len(items))
        self._cache.replace(items, rv)
        self._inc("informer_relists")
        self._last_relist_mono = time.monotonic()  # analysis: allow=TAR503 pump() is the threadless drive mode and is never mixed with start() (see pump docstring)
        if self._wake is not None:
            # The world may have changed arbitrarily across the gap.
            self._wake.set()

    def _watch_once(self) -> None:
        for event in self._watch(
                self._timeout,
                resource_version=self._cache.resource_version):
            relevant = self._cache.apply(event)
            self._inc("informer_events")
            if relevant and self._wake is not None:
                self._wake.set()
            if self._stopped.is_set():
                return

    def _run_once(self) -> None:
        due = (self._last_relist_mono is None
               or time.monotonic() - self._last_relist_mono
               >= self._resync_seconds)
        if not self._cache.synced or due:
            self._relist()
        self._watch_once()

    def run(self) -> None:
        while not self._stopped.is_set():
            try:
                self._run_once()
                self._failure_streak = 0  # clean server-side close
            except Exception as e:  # noqa: BLE001 — crash-only: degrade
                # to LIST-fallback reads until the watch re-syncs
                self._cache.mark_unsynced()
                self._failure_streak += 1
                self._inc("watch_failures")
                if self._tracer is not None:
                    t = self._tracer.clock()
                    self._tracer.record(
                        "informer.watch_failure", start=t, end=t,
                        attrs={"kind": self._cache.kind,
                               "streak": self._failure_streak,
                               "error": f"{e.__class__.__name__}: {e}"})
                level = (logging.WARNING if self._failure_streak == 1
                         else logging.DEBUG)
                log.log(level, "%s watch failed (streak %d): %s; relist "
                        "+ retry with backoff", self._cache.kind,
                        self._failure_streak, e,
                        exc_info=self._failure_streak == 1)
                if self._stopped.wait(self._backoff_seconds()):
                    return


def _list_with_rv(client, kind: str) -> tuple[list[dict], str | None]:
    """Full LIST returning (items, collection resourceVersion).

    Prefers the raw list verbs (which expose the collection's
    resourceVersion — the only safe watch-resume point after a LIST);
    clients without them still work, at the cost of the follow-up watch
    starting from "now" (the periodic resync bounds the gap).
    """
    raw = getattr(client, f"list_{kind}_raw", None)
    if raw is not None:
        body = raw()
        return (body.get("items", []),
                (body.get("metadata") or {}).get("resourceVersion"))
    return getattr(client, f"list_{kind}")(), None


class ClusterInformer:
    """Watch-fed pod + node store, the reconciler's observe source.

    ``pods()``/``nodes()`` serve cached parsed snapshots when the watch
    is healthy and fall back to a direct (memo-parsed) LIST when it is
    not — never worse than the relist-every-pass baseline.  The node
    watch is optional: against a client with only ``watch_pods`` the
    pod side is cached and node reads always fall back.
    """

    def __init__(self, client, wake: threading.Event | None = None,
                 metrics: "Metrics | None" = None,
                 timeout_seconds: int = 60,
                 resync_seconds: float = 900.0,
                 rng: random.Random | None = None,
                 tracer=None):
        from tpu_autoscaler.k8s.objects import parse_node, parse_pod

        self._client = client
        self._metrics = metrics
        self.wake = wake if wake is not None else concurrency.Event()
        self.pod_cache = ObjectCache("pods", parse_pod)
        self.node_cache = ObjectCache("nodes", parse_node)
        self._watches: list[ResourceWatch] = []
        if hasattr(client, "watch_pods"):
            self._watches.append(ResourceWatch(
                self.pod_cache, lambda: _list_with_rv(client, "pods"),
                client.watch_pods, wake=self.wake,
                timeout_seconds=timeout_seconds,
                resync_seconds=resync_seconds, metrics=metrics, rng=rng,
                tracer=tracer))
        if hasattr(client, "watch_nodes"):
            self._watches.append(ResourceWatch(
                self.node_cache, lambda: _list_with_rv(client, "nodes"),
                client.watch_nodes, wake=self.wake,
                timeout_seconds=timeout_seconds,
                resync_seconds=resync_seconds, metrics=metrics, rng=rng,
                tracer=tracer))

    def start(self) -> None:
        for w in self._watches:
            w.start()

    def stop(self) -> None:
        for w in self._watches:
            w.stop()

    def pump(self) -> None:
        """Synchronous drive: relist if unsynced/due, then drain the
        pending watch events, once per resource.  For tests and
        simulations that want informer semantics without threads —
        construct with ``timeout_seconds=0`` so the drain doesn't
        block, and never mix with ``start()``.
        """
        for w in self._watches:
            w._run_once()

    def pods(self):
        """Parsed Pod snapshot (cache when synced, LIST fallback)."""
        snap = self.pod_cache.snapshot()
        if snap is not None:
            return snap
        return self._fallback("pods")

    def nodes(self):
        """Parsed Node snapshot (cache when synced, LIST fallback)."""
        snap = self.node_cache.snapshot()
        if snap is not None:
            return snap
        return self._fallback("nodes")

    def _fallback(self, kind: str):
        from tpu_autoscaler.k8s.objects import parse_node, parse_pod

        if self._metrics is not None:
            self._metrics.inc("informer_fallback_lists")
        parse = parse_pod if kind == "pods" else parse_node
        return [parse(p) for p in getattr(self._client, f"list_{kind}")()]
