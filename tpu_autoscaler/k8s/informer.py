"""Watch-fed object store: the cached observe path for the reconciler.

``Controller.reconcile_once`` used to re-LIST and re-parse every node and
pod from the apiserver on every pass.  At 60 s polls that was noise; the
watch-triggered loop (controller/watch.py) wakes near-instantly, so at
production scale (thousands of pods, 5 s fallback interval) state
collection dominated both controller CPU and apiserver load.  This
module generalizes ``WatchTrigger`` into an informer, the standard
client-go shape:

- one background thread per resource (pods, and nodes when the client
  supports ``watch_nodes``) holds a watch open and applies
  ADDED/MODIFIED/DELETED deltas to a lock-guarded ``ObjectCache``;
- parsing happens once per delta via the (uid, resourceVersion)-memoized
  ``parse_pod``/``parse_node`` (k8s/objects.py), so an unchanged object
  is never re-parsed — a snapshot is an O(n) list copy of already-parsed
  objects;
- a 410 Gone (expired cursor) or any watch failure marks the cache
  unsynced; the watch thread relists (full LIST, counted in
  ``informer_relists``) and resumes watching from the list's
  resourceVersion.  Crash-only: the cache is a pure optimization — a
  cold start, a watch gap, or a crashed thread just mean the next read
  falls back to a direct LIST (``informer_fallback_lists``) until the
  watch re-syncs;
- relevant deltas set the reconcile loop's wake Event, subsuming
  WatchTrigger's level-trigger role.

Consistency model (docs/INFORMER.md): snapshots are immutable lists of
read-only objects, internally consistent as of the cache's cursor, and
at most one watch-delivery behind the apiserver when healthy.  The two
places the reconciler must not act on a stale view — supply that just
went ACTIVE, and a drain cancelled mid-pass — bypass the cache with a
direct LIST (reconciler.py ``_fresh_nodes``).

Thread discipline (TAT2xx, TAR5xx): the watch thread shares state with
readers only through ``ObjectCache`` (every mutation under its Lock),
the wake ``threading.Event``, and the stop Event.  All primitives come
from the ``concurrency`` seam so the deterministic-schedule harness
(testing/sched.py) can drive these exact code paths.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import (
    TYPE_CHECKING,
    Any,
    Callable,
    Hashable,
    Iterable,
    Mapping,
    Sequence,
)

from tpu_autoscaler import concurrency
from tpu_autoscaler.backoff import watch_backoff_seconds

if TYPE_CHECKING:
    from tpu_autoscaler.metrics import Metrics

log = logging.getLogger(__name__)

#: Delta types that change reconcile-relevant state (BOOKMARK and ERROR
#: events move the cursor / signal failure but carry no state change).
RELEVANT_TYPES = frozenset({"ADDED", "MODIFIED", "DELETED"})


class WatchGone(RuntimeError):
    """410 Gone: the watch cursor expired; a full relist is required."""


class WatchError(RuntimeError):
    """An ERROR event on an otherwise-open stream (non-410)."""


#: An indexer maps one parsed object to the index keys it files under
#: (zero or more; e.g. a pod's phase, its gang key, its node).
Indexer = Callable[[Any], Iterable[Hashable]]


@dataclasses.dataclass(frozen=True)
class Fold:
    """An incrementally-maintained aggregate over the cache's objects.

    ``key`` buckets each object (None = excluded); ``value`` is summed
    into the bucket with ``+`` on insert and removed with ``-`` on
    delete, so bucket totals stay current per watch delta instead of
    being recomputed by scanning the store.  ``zero`` is the empty
    aggregate — a bucket returning to it is dropped.
    """

    key: Callable[[Any], Hashable | None]
    value: Callable[[Any], Any]
    zero: Callable[[], Any]


class ObjectCache:
    """Lock-guarded store of one resource's payloads + parsed objects.

    Written only by its resource's watch thread (replace/apply/
    mark_unsynced); read by the reconcile thread (snapshot).  ``synced``
    is False until the first successful relist and again after any
    watch failure — readers fall back to a direct LIST while unsynced.

    Secondary indices (ISSUE 6): ``indexers`` file every object under
    derived keys, maintained *incrementally* in ``apply``/``replace``
    under the same lock as the primary store — so index reads
    (``select``) are O(result), never O(store).  Each index bucket also
    carries a 64-bit XOR **digest** of its members' (key,
    resourceVersion) pairs: XOR is exact and order-free, so the digest
    is maintainable per delta and two equal digests mean the bucket's
    membership+versions are (collision-probability aside) unchanged —
    the primitive the reconciler's delta-driven planning hashes against.
    ``folds`` maintain numeric aggregates (e.g. requested resources per
    node) the same way.  Consumers registered via ``watch_dirty`` get
    the set of dirty tags (e.g. node names) touched since their last
    ``drain_dirty`` — None after a relist/unsync, meaning rebuild.
    """

    def __init__(self, kind: str,
                 parse: Callable[[Mapping[str, Any]], Any],
                 indexers: Mapping[str, Indexer] | None = None,
                 folds: Mapping[str, Fold] | None = None,
                 dirty_tags: Callable[[Any], Iterable[Hashable]]
                 | None = None,
                 reserve: Callable[[int], None] | None = None) -> None:
        self.kind = kind
        self._parse = parse
        # Re-entrant: the index-maintenance helpers (_index_add/_remove,
        # _rebuild_indices) take the lock themselves so every index
        # mutation is lexically guarded, and their callers (apply/
        # replace) hold it around the whole store+index update so the
        # two can never be observed out of step.
        self._lock = concurrency.RLock()
        self._objects: dict[str, dict] = {}
        self._parsed: dict[str, Any] = {}
        self._resource_version: str | None = None
        self._synced = False
        self._indexers: dict[str, Indexer] = dict(indexers or {})
        self._indices: dict[str, dict[Hashable, dict[str, Any]]] = {
            name: {} for name in self._indexers}
        self._idx_digests: dict[str, dict[Hashable, int]] = {
            name: {} for name in self._indexers}
        self._fold_defs: dict[str, Fold] = dict(folds or {})
        self._fold_state: dict[str, dict[Hashable, Any]] = {
            name: {} for name in self._fold_defs}
        self._dirty_tags = dirty_tags
        # consumer -> set of tags touched since its last drain; None =
        # a replace()/mark_unsynced() happened (full rebuild required).
        self._dirty: dict[str, set[Hashable] | None] = {}
        # consumer -> ORDERED ("set"|"del", key) event log since its
        # last drain; None = full rebuild required.  Unlike the tag
        # SETS above, the log preserves delta order, so a consumer can
        # replay it and reproduce the store dict's exact insertion
        # order (a delete + re-add moves a key to the end; a modify
        # keeps its position) — the row-order contract the columnar
        # view (k8s/columnar.py) exports to the planner.  Logged only
        # from ``apply``'s store mutations, never from the index
        # helpers, and capped (``_log_dirty_key``) so an abandoned
        # consumer degrades to rebuild instead of unbounded growth.
        self._dirty_keys: dict[str, list[tuple[str, str]] | None] = {}
        self._reserve = reserve
        # Whole-store XOR digest of (key, resourceVersion) pairs,
        # maintained per delta exactly like the per-bucket index
        # digests: an O(1) identity for "did ANY object change",
        # which the reconciler's pass records hash instead of an
        # O(store) frozenset at the million-pod tier (ISSUE 13).
        self._store_digest = 0

    @staticmethod
    def _key(obj: Mapping[str, Any]) -> str | None:
        meta = obj.get("metadata") or {}
        return meta.get("uid") or meta.get("name") or None

    @property
    def synced(self) -> bool:
        with self._lock:
            return self._synced

    @property
    def resource_version(self) -> str | None:
        with self._lock:
            return self._resource_version

    @property
    def store_digest(self) -> int:
        """O(1) XOR identity of the whole store's (key, rv) pairs."""
        with self._lock:
            return self._store_digest

    # -- index maintenance (self._lock is re-entrant: callers hold it
    #    around the whole store+index update, and each helper takes it
    #    again so every index mutation is lexically lock-guarded) ------

    @staticmethod
    def _contrib(key: str, parsed: Any) -> int:
        """One object's XOR contribution to its buckets' digests."""
        return hash((key, getattr(parsed, "resource_version", None)))

    def _index_add(self, key: str, parsed: Any) -> None:
        contrib = self._contrib(key, parsed)
        with self._lock:
            self._store_digest ^= contrib
            for name, indexer in self._indexers.items():
                index = self._indices[name]
                digests = self._idx_digests[name]
                for ikey in indexer(parsed):
                    index.setdefault(ikey, {})[key] = parsed
                    digests[ikey] = digests.get(ikey, 0) ^ contrib
            for name, fold in self._fold_defs.items():
                fkey = fold.key(parsed)
                if fkey is None:
                    continue
                state = self._fold_state[name]
                cur = state.get(fkey)
                val = fold.value(parsed)
                state[fkey] = val if cur is None else cur + val
            if self._dirty_tags is not None and self._dirty:
                targets = [p for p in self._dirty.values()
                           if p is not None]
                if targets:
                    tags = tuple(self._dirty_tags(parsed))
                    for pending in targets:
                        pending.update(tags)

    def _index_remove(self, key: str, parsed: Any) -> None:
        contrib = self._contrib(key, parsed)
        with self._lock:
            self._store_digest ^= contrib
            for name, indexer in self._indexers.items():
                index = self._indices[name]
                digests = self._idx_digests[name]
                for ikey in indexer(parsed):
                    bucket = index.get(ikey)
                    if bucket is not None:
                        bucket.pop(key, None)
                        if not bucket:
                            del index[ikey]
                            digests.pop(ikey, None)
                            continue
                    digests[ikey] = digests.get(ikey, 0) ^ contrib
            for name, fold in self._fold_defs.items():
                fkey = fold.key(parsed)
                if fkey is None:
                    continue
                state = self._fold_state[name]
                if fkey in state:
                    state[fkey] = state[fkey] - fold.value(parsed)
                    if state[fkey] == fold.zero():
                        del state[fkey]
            if self._dirty_tags is not None and self._dirty:
                targets = [p for p in self._dirty.values()
                           if p is not None]
                if targets:
                    tags = tuple(self._dirty_tags(parsed))
                    for pending in targets:
                        pending.update(tags)

    def _rebuild_indices(self) -> None:
        with self._lock:
            self._indices = {name: {} for name in self._indexers}
            self._idx_digests = {name: {} for name in self._indexers}
            self._fold_state = {name: {} for name in self._fold_defs}
            self._store_digest = 0
            for key, parsed in self._parsed.items():
                self._index_add(key, parsed)

    # -- writes (the resource's watch thread) ----------------------------

    def replace(self, items: Iterable[dict],
                resource_version: str | None) -> None:
        """Install a full LIST result (relist / initial sync).

        ``items`` may be any iterable (generators included) — payloads
        are consumed streaming, never materialized as a second list.
        """
        objects: dict[str, dict] = {}
        parsed: dict[str, Any] = {}
        for item in items:
            key = self._key(item)
            if key is None:
                continue
            objects[key] = item
            # Memoized on (uid, resourceVersion): a relist re-parses
            # only objects that actually changed since last seen.
            parsed[key] = self._parse(item)
        if self._reserve is not None:
            # Size the parse memo for this store (k8s/objects.py): a
            # fixed bound thrashes once the store outgrows it.
            self._reserve(len(objects))
        with self._lock:
            self._objects = objects
            self._parsed = parsed
            self._resource_version = resource_version
            self._synced = True
            # Null the consumers FIRST: the world was replaced, so
            # they rebuild regardless — _index_add skips None entries,
            # sparing O(store) tag-set updates per consumer.
            for consumer in self._dirty:
                self._dirty[consumer] = None
            for consumer in self._dirty_keys:
                self._dirty_keys[consumer] = None
            self._rebuild_indices()

    def apply(self, event: Mapping[str, Any]) -> bool:
        """Apply one watch event; True iff it changed relevant state.

        Raises ``WatchGone`` on a 410 ERROR event (cursor expired, the
        caller must relist) and ``WatchError`` on any other ERROR.
        """
        etype = event.get("type")
        obj = event.get("object") or {}
        if etype == "ERROR":
            if obj.get("code") == 410:
                raise WatchGone(str(obj.get("message", "410 Gone")))
            raise WatchError(str(obj.get("message", "watch ERROR event")))
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        key = self._key(obj)
        if etype in ("ADDED", "MODIFIED") and key is not None:
            parsed = self._parse(obj)
            with self._lock:
                old = self._parsed.get(key)
                if old is not None:
                    self._index_remove(key, old)
                self._objects[key] = dict(obj)
                self._parsed[key] = parsed
                self._index_add(key, parsed)
                self._log_dirty_key("set", key)
                if rv:
                    self._resource_version = rv
            return True
        with self._lock:
            if etype == "DELETED" and key is not None:
                old = self._parsed.pop(key, None)
                self._objects.pop(key, None)
                if old is not None:
                    self._index_remove(key, old)
                    self._log_dirty_key("del", key)
            if rv:
                # BOOKMARK (and DELETED) keep the cursor fresh.
                self._resource_version = rv
        return etype in RELEVANT_TYPES

    def _log_dirty_key(self, op: str, key: str) -> None:
        """Append one ordered store event to every dirty-key consumer.
        Re-entrant like _index_add: the RLock is already held by the
        apply() caller, so taking it here keeps every write lexically
        guarded at zero extra cost.  A consumer whose log outgrows the
        store is nulled — replaying more events than a rebuild costs
        is strictly worse, and the cap bounds an abandoned consumer."""
        with self._lock:
            if not self._dirty_keys:
                return
            cap = max(1024, len(self._objects))
            for consumer, events in self._dirty_keys.items():
                if events is None:
                    continue
                if len(events) >= cap:
                    self._dirty_keys[consumer] = None
                else:
                    events.append((op, key))

    def mark_unsynced(self) -> None:
        """Watch failed or gapped: serve LIST fallbacks until relisted.

        Keeps the cached content (the next relist reuses its parsed
        objects through the memo) but drops the cursor — resuming a
        possibly-gapped watch from the old cursor could miss deltas.
        """
        with self._lock:
            self._synced = False
            self._resource_version = None
            for consumer in self._dirty:
                self._dirty[consumer] = None  # gap of unknown size
            for consumer in self._dirty_keys:
                self._dirty_keys[consumer] = None

    # -- reads (the reconcile thread) ------------------------------------

    def snapshot(self) -> list[Any] | None:
        """Parsed objects as an immutable-by-convention list, or None
        when unsynced (caller falls back to a direct LIST)."""
        with self._lock:
            if not self._synced:
                return None
            return list(self._parsed.values())

    def select(self, index: str, ikey: Hashable) -> list[Any] | None:
        """Objects filed under ``ikey`` in ``index`` — O(result), or
        None when unsynced (caller falls back to a scan)."""
        with self._lock:
            if not self._synced:
                return None
            bucket = self._indices[index].get(ikey)
            return list(bucket.values()) if bucket else []

    def select_many(self, index: str, ikeys: Sequence[Hashable]
                    ) -> list[list[Any]] | None:
        """Bulk ``select`` under ONE lock acquisition (the per-call
        lock round-trip dominates at fleet-scale churn), or None when
        unsynced."""
        with self._lock:
            if not self._synced:
                return None
            buckets = self._indices[index]
            return [list(b.values()) if (b := buckets.get(k)) else []
                    for k in ikeys]

    def snapshot_and_select(self, index: str, ikey: Hashable
                            ) -> tuple[list[Any], list[Any]] | None:
        """A full snapshot plus one index bucket, consistent with each
        other (single lock acquisition), or None when unsynced."""
        with self._lock:
            if not self._synced:
                return None
            bucket = self._indices[index].get(ikey)
            return (list(self._parsed.values()),
                    list(bucket.values()) if bucket else [])

    def snapshot_with_digest(self) -> tuple[list[Any], int] | None:
        """``snapshot`` plus the store digest describing EXACTLY that
        snapshot (one lock hold — a digest read after the lock drops
        could describe a later world; review-found, ISSUE 13)."""
        with self._lock:
            if not self._synced:
                return None
            return list(self._parsed.values()), self._store_digest

    def snapshot_items_with_digest(self) -> tuple[
            list[tuple[str, Any]], int] | None:
        """``snapshot_with_digest`` with each object's store KEY — the
        columnar view's full-rebuild read (its rows are keyed exactly
        like the store so dirty-key events can address them)."""
        with self._lock:
            if not self._synced:
                return None
            return list(self._parsed.items()), self._store_digest

    def snapshot_select_digest(self, index: str, ikey: Hashable
                               ) -> tuple[list[Any], list[Any],
                                          int] | None:
        """``snapshot_and_select`` plus the matching store digest,
        all under one lock hold."""
        with self._lock:
            if not self._synced:
                return None
            bucket = self._indices[index].get(ikey)
            return (list(self._parsed.values()),
                    list(bucket.values()) if bucket else [],
                    self._store_digest)

    def index_keys(self, index: str) -> list[Hashable] | None:
        with self._lock:
            if not self._synced:
                return None
            return list(self._indices[index])

    def digest(self, index: str, ikey: Hashable) -> int:
        """The bucket's XOR membership digest (0 when empty/absent)."""
        with self._lock:
            return self._idx_digests[index].get(ikey, 0)

    def digests(self, index: str,
                ikeys: Sequence[Hashable]) -> list[int]:
        """Bulk ``digest`` under one lock acquisition."""
        with self._lock:
            digests = self._idx_digests[index]
            return [digests.get(k, 0) for k in ikeys]

    def fold_value(self, name: str, key: Hashable,
                   default: Any = None) -> Any:
        with self._lock:
            return self._fold_state[name].get(key, default)

    def fold_values(self, name: str, keys: Sequence[Hashable],
                    default: Any = None) -> list[Any]:
        with self._lock:
            state = self._fold_state[name]
            return [state.get(k, default) for k in keys]

    def watch_dirty(self, consumer: str) -> None:
        """Register ``consumer`` for dirty-tag tracking (starts in the
        rebuild-required state)."""
        with self._lock:
            self._dirty[consumer] = None

    def unwatch_dirty(self, consumer: str) -> None:
        """Deregister a dirty-tag consumer (every registration costs
        O(1) tag-set work per delta, so abandoned views must detach)."""
        with self._lock:
            self._dirty.pop(consumer, None)

    def drain_dirty(self, consumer: str) -> set[Hashable] | None:
        """Tags touched since the consumer's last drain, or None when a
        replace/unsync invalidated everything (full rebuild needed)."""
        with self._lock:
            pending = self._dirty.get(consumer)
            self._dirty[consumer] = set()
            return pending

    def watch_dirty_keys(self, consumer: str) -> None:
        """Register ``consumer`` for ordered dirty-KEY event tracking
        (starts in the rebuild-required state)."""
        with self._lock:
            self._dirty_keys[consumer] = None

    def unwatch_dirty_keys(self, consumer: str) -> None:
        with self._lock:
            self._dirty_keys.pop(consumer, None)

    def drain_dirty_keys(self, consumer: str) -> tuple[
            list[tuple[str, str]] | None, dict[str, Any], int, bool]:
        """One lock hold returning ``(events, parsed_by_key, digest,
        synced)``: the ordered event log since the last drain (None =
        rebuild required), the CURRENT parsed object for every key a
        "set" event names (a key absent from the map was deleted again
        before the drain — its later "del" event makes the replay net
        out), and the store digest describing exactly the drained
        prefix.  Replaying the events reproduces the store dict's
        insertion order (docs/PLANNER.md row-order contract)."""
        with self._lock:
            events = self._dirty_keys.get(consumer)
            self._dirty_keys[consumer] = []
            if events is None:
                return None, {}, self._store_digest, self._synced
            lookup: dict[str, Any] = {}
            for op, key in events:
                if op == "set" and key not in lookup:
                    parsed = self._parsed.get(key)
                    if parsed is not None:
                        lookup[key] = parsed
            return events, lookup, self._store_digest, self._synced

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


# ---- standard index configuration --------------------------------------
#
# The reconciler's demand/supply queries, expressed as indices so a pass
# reads exactly what it needs instead of scanning a full snapshot:
# pods by phase / gang key / node / unschedulability; nodes by supply
# pool / accelerator class / name / readiness.  The ``usage`` fold keeps
# per-node requested resources current per delta — free capacity becomes
# an O(nodes-of-interest) read instead of an O(pods) scan.

#: Index-key sentinel for the unschedulable-pods bucket.
PENDING = "pending"


def _unit_key_of(node: Any) -> str:
    """The node's supply-unit key — the k8s/units.py grouping rule."""
    from tpu_autoscaler.topology.catalog import SLICE_ID_LABEL

    if node.is_tpu and node.slice_id:
        return node.slice_id
    return node.labels.get(SLICE_ID_LABEL) or node.name


def _accel_class_of(node: Any) -> str:
    """Supply class for delta-planning digests: the accelerator label
    for TPU nodes, 'cpu' for everything else."""
    return node.tpu_accelerator or ("cpu" if not node.is_tpu else "tpu")


def make_pod_cache(reserve: Callable[[int], None] | None = None
                   ) -> ObjectCache:
    from tpu_autoscaler.k8s.objects import parse_pod
    from tpu_autoscaler.k8s.resources import ResourceVector

    def usage_key(p):
        return (p.node_name
                if p.node_name and p.phase in ("Pending", "Running")
                else None)

    return ObjectCache(
        "pods", parse_pod,
        indexers={
            "phase": lambda p: (p.phase,) if p.phase else (),
            "gang": lambda p: (p.gang_key,),
            "node": lambda p: (p.node_name,) if p.node_name else (),
            "unschedulable": lambda p: ((PENDING,) if p.is_unschedulable
                                        else ()),
        },
        folds={"usage": Fold(key=usage_key, value=lambda p: p.resources,
                             zero=ResourceVector)},
        dirty_tags=lambda p: (p.node_name,) if p.node_name else (),
        reserve=reserve)


def make_node_cache(reserve: Callable[[int], None] | None = None
                    ) -> ObjectCache:
    from tpu_autoscaler.k8s.objects import parse_node

    return ObjectCache(
        "nodes", parse_node,
        indexers={
            "name": lambda n: (n.name,),
            "pool": lambda n: (_unit_key_of(n),),
            "accel": lambda n: (_accel_class_of(n),),
            "ready": lambda n: (bool(n.is_ready
                                     and not n.unschedulable),),
        },
        dirty_tags=lambda n: (n.name,),
        reserve=reserve)


@dataclasses.dataclass
class PoolState:
    """One supply pool's incrementally-maintained summary."""

    nodes: set = dataclasses.field(default_factory=set)
    tpu: bool = False
    chips: float = 0.0       # allocatable TPU chips across the pool
    ready: int = 0           # Ready + schedulable member count
    used_chips: float = 0.0  # TPU chips requested by bound pods

    @property
    def free_slice(self) -> bool:
        """THE free-slice predicate (engine/columnar.py
        ``slice_is_free``) — the same function ``planner._free_slices``
        and the columnar ``slice_free_mask`` evaluate, so the three can
        never drift (chip counts are integers, so the incremental float
        arithmetic is exact)."""
        from tpu_autoscaler.engine.columnar import slice_is_free

        return slice_is_free(bool(self.tpu), len(self.nodes),
                             self.ready, self.used_chips)


class CapacityView:
    """Per-node free capacity + per-pool summary, maintained O(churn).

    Owned by ONE consumer thread (the reconcile loop, or a bench): each
    ``refresh()`` drains the caches' dirty-tag sets (node names touched
    since last time) and recomputes only those nodes' entries and their
    pools' summaries; a relist/unsync rebuilds from scratch.  Between
    refreshes the view's dicts are plain thread-local state.

    ``free`` matches ``engine.fitter.free_capacity(nodes, pods)`` up to
    float associativity on the cpu/memory axes (the ``usage`` fold adds
    and subtracts per delta instead of re-summing); chip axes are
    integral and therefore exact — which is what ``free_slice`` keys on.
    """

    _SEQ = [0]

    def __init__(self, node_cache: ObjectCache,
                 pod_cache: ObjectCache) -> None:
        self._node_cache = node_cache
        self._pod_cache = pod_cache
        CapacityView._SEQ[0] += 1
        self._consumer = f"capacity-{CapacityView._SEQ[0]}"
        node_cache.watch_dirty(self._consumer)
        pod_cache.watch_dirty(self._consumer)
        #: node name -> free ResourceVector (Ready + schedulable only)
        self.free: dict[str, Any] = {}
        #: pool key -> PoolState
        self.pools: dict[str, PoolState] = {}
        self._node_pool: dict[str, str] = {}  # name -> its pool key

    def close(self) -> None:
        """Detach from the caches (a dangling registration would keep
        costing tag-set work on every delta forever)."""
        self._node_cache.unwatch_dirty(self._consumer)
        self._pod_cache.unwatch_dirty(self._consumer)

    def refresh(self) -> bool:
        """Fold pending churn into the view; False when either cache is
        unsynced (view contents are then stale — don't use them)."""
        if not (self._node_cache.synced and self._pod_cache.synced):
            # Leave the dirty sets accumulating; the next synced refresh
            # sees None (the unsync reset them) and rebuilds.
            return False
        node_dirty = self._node_cache.drain_dirty(self._consumer)
        pod_dirty = self._pod_cache.drain_dirty(self._consumer)
        if node_dirty is None or pod_dirty is None:
            names = self._node_cache.index_keys("name")
            if names is None:
                return False
            self.free.clear()
            self.pools.clear()
            self._node_pool.clear()
            dirty = set(names)
        else:
            dirty = node_dirty | pod_dirty
        if dirty:
            self._refresh_nodes(list(dirty))
        return True

    def _refresh_nodes(self, names: list[str]) -> None:
        """Recompute the dirty nodes' free entries and recount each
        affected pool ONCE, with bulk (single-lock) cache reads — the
        per-call lock round-trip is the refresh cost at churn scale."""
        hits = self._node_cache.select_many("name", names) or []
        usages = self._pod_cache.fold_values("usage", names)
        touched_pools: set[str] = set()
        nodes_by_name: dict[str, Any] = {}
        for name, node_hits, usage in zip(names, hits, usages):
            node = node_hits[0] if node_hits else None
            nodes_by_name[name] = node
            old_pool = self._node_pool.pop(name, None)
            if old_pool is not None:
                touched_pools.add(old_pool)
            self.free.pop(name, None)
            if node is None:
                continue  # deleted (or a pod's node never existed)
            if node.is_ready and not node.unschedulable:
                self.free[name] = (node.allocatable - usage
                                   if usage is not None
                                   else node.allocatable)
            pool_key = _unit_key_of(node)
            self._node_pool[name] = pool_key
            touched_pools.add(pool_key)
        if touched_pools:
            self._recount_pools(sorted(touched_pools))

    def _recount_pools(self, pool_keys: list[str]) -> None:
        from tpu_autoscaler.topology.catalog import TPU_RESOURCE

        member_lists = (self._node_cache.select_many("pool", pool_keys)
                        or [[] for _ in pool_keys])
        all_names = [n.name for members in member_lists for n in members]
        usage_by_name = dict(zip(
            all_names, self._pod_cache.fold_values("usage", all_names)))
        for pool_key, members in zip(pool_keys, member_lists):
            if not members:
                self.pools.pop(pool_key, None)
                continue
            pool = self.pools.setdefault(pool_key, PoolState())
            pool.nodes = set()
            pool.tpu = False
            pool.chips = 0.0
            pool.ready = 0
            pool.used_chips = 0.0
            for node in members:
                pool.nodes.add(node.name)
                pool.tpu = pool.tpu or node.is_tpu
                pool.chips += node.allocatable.get(TPU_RESOURCE)
                if node.is_ready and not node.unschedulable:
                    pool.ready += 1
                usage = usage_by_name.get(node.name)
                if usage is not None:
                    pool.used_chips += usage.get(TPU_RESOURCE)

    def free_slices(self) -> set[str]:
        """Pool keys whose every host is Ready, schedulable, chip-idle."""
        return {key for key, pool in self.pools.items() if pool.free_slice}


class ResourceWatch(concurrency.Thread):
    """One resource's relist+watch loop, feeding its ObjectCache.

    Failure semantics match WatchTrigger (VERDICT r1 item 6): bounded
    exponential backoff with full jitter, ``watch_failures`` counted,
    only the first failure of a streak logged at WARNING.  On top of
    that: every failure (and every 410) marks the cache unsynced so the
    next loop iteration relists before re-watching — counted in
    ``informer_relists``.
    """

    def __init__(self, cache: ObjectCache,
                 list_fn: Callable[[], tuple[list[dict], str | None]],
                 watch_fn: Callable[..., Iterable[Mapping[str, Any]]],
                 wake: threading.Event | None = None,
                 timeout_seconds: int = 60,
                 resync_seconds: float = 900.0,
                 metrics: "Metrics | None" = None,
                 rng: random.Random | None = None,
                 tracer=None):
        super().__init__(daemon=True, name=f"{cache.kind}-informer")
        self._cache = cache
        self._list = list_fn
        self._watch = watch_fn
        self._wake = wake
        self._timeout = timeout_seconds
        self._resync_seconds = resync_seconds
        self._stopped = concurrency.Event()
        self._metrics = metrics
        # Tracer (obs/trace.py): relists and failures are span-worthy
        # (rare, and exactly what a slow-detection investigation needs);
        # per-event deltas are NOT traced — the hot path stays hot.
        self._tracer = tracer
        self._rng = rng or random.Random()  # analysis: allow=TAD902 watch-backoff jitter is production-only entropy BY DESIGN: replay drives recorded events (never the live watch loop) and the harness injects a seeded rng; jitter never reaches replayed bytes
        self._failure_streak = 0
        self._last_relist_mono: float | None = None

    def stop(self) -> None:
        self._stopped.set()

    # -- internals, factored for testability (all thread-owned) ----------

    def _backoff_seconds(self) -> float:
        return watch_backoff_seconds(self._failure_streak, self._rng)

    def _inc(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.inc(name)

    def _relist(self) -> None:
        from tpu_autoscaler.obs import maybe_span

        with maybe_span(self._tracer, "informer.relist",
                        attrs={"kind": self._cache.kind}) as span:
            items, rv = self._list()
            if span is not None:
                # Via the tracer lock: /debugz may be copying this
                # still-open span concurrently.
                self._tracer.annotate(span, objects=len(items))
        self._cache.replace(items, rv)
        self._inc("informer_relists")
        if self._metrics is not None:
            # Memo health after the relist: a hit rate sinking toward 0
            # at steady state means the LRU is undersized for the store
            # (docs/OPERATIONS.md).
            from tpu_autoscaler.k8s.objects import parse_cache_info

            info = parse_cache_info()
            self._metrics.set_gauge("parse_cache_entries",
                                    info["pods"] + info["nodes"])
            self._metrics.set_gauge("parse_cache_hit_rate",
                                    info["hit_rate"])
        self._last_relist_mono = time.monotonic()  # analysis: allow=TAR503,TAD901 pump() is the threadless drive mode and is never mixed with start() (see pump docstring); the resync clock paces the LIVE watch thread only — replay feeds recorded events and never reaches the relist timer
        if self._wake is not None:
            # The world may have changed arbitrarily across the gap.
            self._wake.set()

    def _watch_once(self) -> None:
        for event in self._watch(
                self._timeout,
                resource_version=self._cache.resource_version):
            relevant = self._cache.apply(event)
            self._inc("informer_events")
            if relevant and self._wake is not None:
                self._wake.set()
            if self._stopped.is_set():
                return

    def _run_once(self) -> None:
        due = (self._last_relist_mono is None
               or time.monotonic() - self._last_relist_mono  # analysis: allow=TAD901 resync pacing of the live watch thread BY DESIGN: replay never drives _run_once — it feeds recorded events through apply/replace
               >= self._resync_seconds)
        if not self._cache.synced or due:
            self._relist()
        self._watch_once()

    def run(self) -> None:
        while not self._stopped.is_set():
            try:
                self._run_once()
                self._failure_streak = 0  # clean server-side close
            except Exception as e:  # noqa: BLE001 — crash-only: degrade
                # to LIST-fallback reads until the watch re-syncs
                self._cache.mark_unsynced()
                self._failure_streak += 1
                self._inc("watch_failures")
                if self._tracer is not None:
                    t = self._tracer.clock()
                    self._tracer.record(
                        "informer.watch_failure", start=t, end=t,
                        attrs={"kind": self._cache.kind,
                               "streak": self._failure_streak,
                               "error": f"{e.__class__.__name__}: {e}"})
                level = (logging.WARNING if self._failure_streak == 1
                         else logging.DEBUG)
                log.log(level, "%s watch failed (streak %d): %s; relist "
                        "+ retry with backoff", self._cache.kind,
                        self._failure_streak, e,
                        exc_info=self._failure_streak == 1)
                if self._stopped.wait(self._backoff_seconds()):
                    return


def _list_with_rv(client, kind: str) -> tuple[list[dict], str | None]:
    """Full LIST returning (items, collection resourceVersion).

    Prefers the raw list verbs (which expose the collection's
    resourceVersion — the only safe watch-resume point after a LIST);
    clients without them still work, at the cost of the follow-up watch
    starting from "now" (the periodic resync bounds the gap).
    """
    raw = getattr(client, f"list_{kind}_raw", None)
    if raw is not None:
        body = raw()
        return (body.get("items", []),
                (body.get("metadata") or {}).get("resourceVersion"))
    return getattr(client, f"list_{kind}")(), None


class ClusterInformer:
    """Watch-fed pod + node store, the reconciler's observe source.

    ``pods()``/``nodes()`` serve cached parsed snapshots when the watch
    is healthy and fall back to a direct (memo-parsed) LIST when it is
    not — never worse than the relist-every-pass baseline.  The node
    watch is optional: against a client with only ``watch_pods`` the
    pod side is cached and node reads always fall back.

    On top of the snapshots, the caches carry the standard secondary
    indices (``make_pod_cache``/``make_node_cache``): the reconciler
    pulls Unschedulable pods (``pods_and_pending``), per-node pod
    digests (``pod_node_digests``) and per-accelerator-class supply
    digests (``supply_digests``) without scanning the store, and the
    memoized ``capacity_view()`` serves free capacity O(churn).
    """

    def __init__(self, client, wake: threading.Event | None = None,
                 metrics: "Metrics | None" = None,
                 timeout_seconds: int = 60,
                 resync_seconds: float = 900.0,
                 rng: random.Random | None = None,
                 tracer=None):
        import functools

        from tpu_autoscaler.k8s.objects import reserve_parse_cache

        self._client = client
        self._metrics = metrics
        self.wake = wake if wake is not None else concurrency.Event()
        self.pod_cache = make_pod_cache(
            reserve=functools.partial(reserve_parse_cache, "pods"))
        self.node_cache = make_node_cache(
            reserve=functools.partial(reserve_parse_cache, "nodes"))
        self._watches: list[ResourceWatch] = []
        if hasattr(client, "watch_pods"):
            self._watches.append(ResourceWatch(
                self.pod_cache, lambda: _list_with_rv(client, "pods"),
                client.watch_pods, wake=self.wake,
                timeout_seconds=timeout_seconds,
                resync_seconds=resync_seconds, metrics=metrics, rng=rng,
                tracer=tracer))
        if hasattr(client, "watch_nodes"):
            self._watches.append(ResourceWatch(
                self.node_cache, lambda: _list_with_rv(client, "nodes"),
                client.watch_nodes, wake=self.wake,
                timeout_seconds=timeout_seconds,
                resync_seconds=resync_seconds, metrics=metrics, rng=rng,
                tracer=tracer))

    def start(self) -> None:
        for w in self._watches:
            w.start()

    def stop(self) -> None:
        for w in self._watches:
            w.stop()

    def pump(self) -> None:
        """Synchronous drive: relist if unsynced/due, then drain the
        pending watch events, once per resource.  For tests and
        simulations that want informer semantics without threads —
        construct with ``timeout_seconds=0`` so the drain doesn't
        block, and never mix with ``start()``.

        Failure semantics mirror the threaded loop (ISSUE 7: the chaos
        engine drives apiserver brownouts through here): an error marks
        the cache unsynced — reads degrade to the LIST fallback — and
        counts ``watch_failures``; the next pump relists.
        """
        for w in self._watches:
            try:
                w._run_once()
            except Exception:  # noqa: BLE001 — crash-only: degrade to
                # the LIST fallback, like run().  (The backoff streak is
                # run()'s alone: pump mode is never mixed with threads.)
                w._cache.mark_unsynced()
                w._inc("watch_failures")
                log.debug("pump: %s failed; unsynced until next relist",
                          w._cache.kind, exc_info=True)

    def pods(self):
        """Parsed Pod snapshot (cache when synced, LIST fallback)."""
        snap = self.pod_cache.snapshot()
        if snap is not None:
            return snap
        return self._fallback("pods")

    def nodes(self):
        """Parsed Node snapshot (cache when synced, LIST fallback)."""
        snap = self.node_cache.snapshot()
        if snap is not None:
            return snap
        return self._fallback("nodes")

    def _fallback(self, kind: str):
        from tpu_autoscaler.k8s.objects import parse_node, parse_pod

        if self._metrics is not None:
            self._metrics.inc("informer_fallback_lists")
        parse = parse_pod if kind == "pods" else parse_node
        return [parse(p) for p in getattr(self._client, f"list_{kind}")()]

    # -- indexed reads (ISSUE 6) -----------------------------------------

    def pods_and_pending(self):
        """(all pods, Unschedulable pods) — mutually consistent (one
        lock hold), with the pending side read from the index instead
        of a full-store scan.  Falls back to LIST + scan when unsynced.
        """
        both = self.pod_cache.snapshot_and_select("unschedulable",
                                                  PENDING)
        if both is not None:
            return both
        pods = self._fallback("pods")
        return pods, [p for p in pods if p.is_unschedulable]

    def observe_with_digest(self):
        """One pass's full cache-backed world view plus its identity:
        ``(nodes, pods, pending, digest)`` where ``digest`` is built
        from each cache's store digest captured UNDER the same lock
        hold as its snapshot — so a pass record can never be stamped
        with a world the pass did not observe (watch threads keep the
        caches moving mid-pass).  None when either cache is unsynced;
        the caller falls back to the LIST paths and the legacy
        per-list hash."""
        got = self.observe_with_digests()
        if got is None:
            return None
        return got[:4]

    def observe_with_digests(self):
        """``observe_with_digest`` plus the RAW per-cache digests:
        ``(nodes, pods, pending, digest, node_digest, pod_digest)`` —
        the reconciler compares the raw pair against the stamps on the
        columnar view's exported state to prove the state describes
        exactly this observation (docs/PLANNER.md).  None when either
        cache is unsynced."""
        node_snap = self.node_cache.snapshot_with_digest()
        if node_snap is None:
            return None
        pod_snap = self.pod_cache.snapshot_select_digest(
            "unschedulable", PENDING)
        if pod_snap is None:
            return None
        nodes, node_digest = node_snap
        pods, pending, pod_digest = pod_snap
        return (nodes, pods, pending,
                hash(("informer", pod_digest, node_digest)),
                node_digest, pod_digest)

    def unready_nodes(self):
        """Parsed nodes currently NotReady or cordoned — the node-failure
        delta surface (ISSUE 7): the repair detector reads the readiness
        index in O(failures) instead of re-deriving per-node health from
        the full snapshot.  None while the node cache is unsynced."""
        return self.node_cache.select("ready", False)

    def pod_node_digests(self, names: Sequence[str]) -> list[int] | None:
        """Per-node pod-membership digests (None while unsynced) — the
        O(1)-per-node input to delta-planning supply digests."""
        if not self.pod_cache.synced:
            return None
        return self.pod_cache.digests("node", names)

    def supply_digests(self, nodes) -> dict[str, int] | None:
        """Per-accelerator-class digest of supply-relevant state: every
        node's (uid, resourceVersion, readiness, cordon) plus the
        digest of the pods bound to it.  A gang's candidate supply is
        an accelerator class (or 'cpu'), so comparing these across
        passes detects any change that could alter that gang's plan —
        in O(nodes), with the pod side O(1) per node via the index.
        None while the pod cache is unsynced (caller plans fully).
        """
        pod_digests = self.pod_node_digests([n.name for n in nodes])
        if pod_digests is None:
            return None
        out: dict[str, int] = {}
        for node, pod_digest in zip(nodes, pod_digests):
            accel = _accel_class_of(node)
            contrib = hash((node.uid or node.name, node.resource_version,
                            node.is_ready, node.unschedulable))
            out[accel] = out.get(accel, 0) ^ contrib ^ pod_digest
        return out

    def capacity_view(self) -> CapacityView:
        """THE informer's incrementally-maintained capacity view
        (single-consumer; call ``refresh()`` per pass).  Memoized: each
        CapacityView registers a dirty-tag consumer on both caches, so
        a view-per-call would leak tag-set work on every delta."""
        view = getattr(self, "_capacity_view", None)
        if view is None:
            view = self._capacity_view = CapacityView(self.node_cache,
                                                      self.pod_cache)
        return view

    def columnar_view(self):
        """THE informer's incrementally-maintained columnar planner
        state (k8s/columnar.py; single-consumer, ``refresh()`` per
        pass).  Memoized for the same reason as ``capacity_view``."""
        view = getattr(self, "_columnar_view", None)
        if view is None:
            from tpu_autoscaler.k8s.columnar import ColumnarView

            view = self._columnar_view = ColumnarView(self.node_cache,
                                                      self.pod_cache)
        return view
