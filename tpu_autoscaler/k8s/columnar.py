"""Incrementally-maintained columnar planner state (docs/PLANNER.md).

``engine/columnar.py`` defines :class:`ColumnarState` — a pure
struct-of-arrays *value* the planner's hot loops run over — and its
from-scratch constructor ``ColumnarState.build`` (the churn suite's
oracle).  Rebuilding that value every pass would cost O(pods); this
module is the informer-side twin of :class:`~tpu_autoscaler.k8s.informer.
CapacityView`: a :class:`ColumnarView` registers on both object caches
and folds churn into grow-only column buffers, so a steady-state refresh
costs O(deltas) and an export costs one gather.

Maintenance contract:

* **Pod side is incremental** (the million-row side).  The pod cache's
  ordered dirty-KEY event log (``ObjectCache.drain_dirty_keys``)
  preserves delta order, so replaying it reproduces the store dict's
  exact insertion order: a MODIFIED pod updates its row in place, a
  DELETED pod marks its row dead (live rows keep their relative order),
  a re-ADDED pod appends — exactly the order ``snapshot()`` will list.
  Dead rows are skipped at export and compacted away past a threshold.
* **Node side rebuilds on node churn** (the thousand-row side).  Any
  node dirty tag triggers an O(nodes) column rebuild; pod rows are then
  relinked through the view's own name->rows reverse index — only names
  whose row mapping actually changed are touched.
* **Export is copy-on-read.**  ``refresh()`` returns a
  :class:`ColumnarState` whose arrays are gathered copies of the live
  rows, stamped with the per-cache store digests captured under the
  same lock hold as the deltas they describe — the reconciler attaches
  the state to a pass only when those stamps equal the digests of the
  observation the pass planned from, and falls back to the Python
  planner otherwise (crash-only, like every informer optimization).
  The returned state is cached until the next mutation.

Single-consumer, like CapacityView: the reconcile loop (or a bench)
owns the view and calls ``refresh()`` at most once per pass.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from tpu_autoscaler.engine.columnar import (
    _ACTIVE_PHASES,
    ColumnarState,
    NodeTemplates,
    _allocatable_axes,
    build_groups,
    pod_sig,
)
from tpu_autoscaler.k8s.informer import ObjectCache
from tpu_autoscaler.k8s.units import unit_key_of
from tpu_autoscaler.topology.catalog import TPU_RESOURCE

_MIN_CAP = 64


class ColumnarView:
    """O(churn) maintenance of the planner's struct-of-arrays state."""

    _SEQ = [0]

    def __init__(self, node_cache: ObjectCache,
                 pod_cache: ObjectCache) -> None:
        self._node_cache = node_cache
        self._pod_cache = pod_cache
        ColumnarView._SEQ[0] += 1
        self._consumer = f"columnar-{ColumnarView._SEQ[0]}"
        node_cache.watch_dirty(self._consumer)
        pod_cache.watch_dirty_keys(self._consumer)
        self.templates = NodeTemplates()  # grow-only across rebuilds
        # -- node side (rebuilt wholesale on node churn) --
        self._nodes: list[Any] = []
        self._n_ready = np.zeros(0, bool)
        self._n_sched = np.zeros(0, bool)
        self._n_is_tpu = np.zeros(0, bool)
        self._n_chips = np.zeros(0, np.int64)
        self._n_tmpl = np.zeros(0, np.int32)
        self._slice_gid = np.zeros(0, np.int32)
        self._unit_gid = np.zeros(0, np.int32)
        self._slices = build_groups([], self._n_tmpl, self._n_chips)[0]
        self._units = build_groups([], self._n_tmpl, self._n_chips)[0]
        self._node_row_of: dict[str, int] = {}
        self._node_digest: int | None = None
        # -- pod side (incremental; grow-only buffers + dead marks) --
        self._n = 0
        self._cap = _MIN_CAP
        self._dead = np.zeros(self._cap, bool)
        self._dead_count = 0
        self._p_node_row = np.zeros(self._cap, np.int32)
        self._p_has_node = np.zeros(self._cap, bool)
        self._p_active = np.zeros(self._cap, bool)
        self._p_workload = np.zeros(self._cap, bool)
        self._p_tpu = np.zeros(self._cap, np.float64)
        self._p_tpu_chips = np.zeros(self._cap, np.int64)
        self._p_gang = np.zeros(self._cap, np.int32)
        self._p_ns = np.zeros(self._cap, np.int32)
        self._p_axes: list[Any] = []
        self._keys: list[str] = []           # store key per row
        self._sigs: list[tuple] = []         # (uid-or-name, rv) per row
        self._names: list[str | None] = []   # node name per row
        self._row_of: dict[str, int] = {}
        self._rows_by_nodename: dict[str, set[int]] = {}
        self._pod_digest: int | None = None
        # -- interned ids (grow-only; shared with exported states) --
        self._gang_keys: list[Any] = []
        self._gang_ids: dict[Any, int] = {}
        self._ns_keys: list[str] = []
        self._ns_ids: dict[str, int] = {}
        self._axes: list[str] = []
        self._axis_ids: dict[str, int] = {}
        self._export: ColumnarState | None = None
        #: Counters the reconciler copies into metrics after refresh.
        self.rebuilds = 0
        self.events_applied = 0

    def close(self) -> None:
        """Detach from the caches (a dangling registration costs per-
        delta log work forever)."""
        self._node_cache.unwatch_dirty(self._consumer)
        self._pod_cache.unwatch_dirty_keys(self._consumer)

    # -- the per-pass entry point -----------------------------------------

    def refresh(self) -> ColumnarState | None:
        """Fold pending churn in; the current state, or None when
        either cache is unsynced (use the Python planner this pass)."""
        if not (self._node_cache.synced and self._pod_cache.synced):
            return None
        node_dirty = self._node_cache.drain_dirty(self._consumer)
        if node_dirty is None or node_dirty:
            if not self._rebuild_nodes():
                return None
        events, lookup, digest, synced = (
            self._pod_cache.drain_dirty_keys(self._consumer))
        if not synced:
            return None
        if events is None:
            if not self._rebuild_pods():
                return None
        else:
            if events:
                self._replay(events, lookup)
                self._export = None
                self.events_applied += len(events)
            self._pod_digest = digest
        if self._dead_count > max(1024,
                                  (self._n - self._dead_count) // 8):
            self._compact()
        return self._export_state()

    # -- node side ---------------------------------------------------------

    def _rebuild_nodes(self) -> bool:
        snap = self._node_cache.snapshot_with_digest()
        if snap is None:
            return False
        nodes, digest = snap
        n = len(nodes)
        n_ready = np.zeros(n, bool)
        n_sched = np.zeros(n, bool)
        n_is_tpu = np.zeros(n, bool)
        n_chips = np.zeros(n, np.int64)
        n_tmpl = np.zeros(n, np.int32)
        slice_keys: list[str | None] = [None] * n
        unit_keys: list[str | None] = [None] * n
        row_of: dict[str, int] = {}
        templates = self.templates
        for i, nd in enumerate(nodes):
            n_ready[i] = nd.is_ready
            n_sched[i] = not nd.unschedulable
            n_is_tpu[i] = nd.is_tpu
            tid = templates.template_of(nd)
            n_tmpl[i] = tid
            n_chips[i] = templates.chips[tid]
            if nd.is_tpu and nd.slice_id:
                slice_keys[i] = nd.slice_id
            unit_keys[i] = unit_key_of(nd)
            row_of[nd.name] = i
        self._slices, self._slice_gid = build_groups(slice_keys, n_tmpl,
                                                     n_chips)
        self._units, self._unit_gid = build_groups(unit_keys, n_tmpl,
                                                   n_chips)
        # The free-vector twin iterates state.axes — allocatable-only
        # axes (no pod requests them) must still be registered.
        for axis in _allocatable_axes(templates):
            self._ensure_axis(axis)
        self._nodes = nodes
        self._n_ready, self._n_sched = n_ready, n_sched
        self._n_is_tpu, self._n_chips = n_is_tpu, n_chips
        self._n_tmpl = n_tmpl
        # Relink bound pods whose node row moved (or whose node just
        # appeared/vanished) through the view's own reverse index — no
        # cache reads, so rows and links can never be inconsistent.
        old_row_of = self._node_row_of
        for name, rows in self._rows_by_nodename.items():
            new = row_of.get(name, -1)
            if new != old_row_of.get(name, -1) and rows:
                idx = np.fromiter(rows, np.int64, count=len(rows))
                self._p_node_row[idx] = new
        self._node_row_of = row_of
        self._node_digest = digest
        self._export = None
        self.rebuilds += 1
        return True

    # -- pod side ----------------------------------------------------------

    def _rebuild_pods(self) -> bool:
        snap = self._pod_cache.snapshot_items_with_digest()
        if snap is None:
            return False
        items, digest = snap
        n = len(items)
        self._n = 0
        self._cap = max(_MIN_CAP, n)
        self._dead = np.zeros(self._cap, bool)
        self._dead_count = 0
        for field in ("_p_node_row", "_p_has_node", "_p_active",
                      "_p_workload", "_p_tpu", "_p_tpu_chips",
                      "_p_gang", "_p_ns"):
            old = getattr(self, field)
            setattr(self, field, np.zeros(self._cap, old.dtype))
        self._p_axes = [np.zeros(self._cap, np.float64)
                        for _ in self._axes]
        self._keys, self._sigs, self._names = [], [], []
        self._row_of = {}
        self._rows_by_nodename = {}
        for key, p in items:
            self._append_row(key, p)
        self._pod_digest = digest
        self._export = None
        self.rebuilds += 1
        return True

    def _replay(self, events: list[tuple[str, str]],
                lookup: dict[str, Any]) -> None:
        for op, key in events:
            row = self._row_of.get(key)
            if op == "del":
                if row is not None:
                    self._kill_row(key, row)
                continue
            p = lookup.get(key)
            if p is None:
                # Set then deleted again before the drain: the later
                # "del" event (or this no-op) nets the key out.
                continue
            if row is None:
                self._append_row(key, p)     # new key -> dict appends
            else:
                self._update_row(row, p)     # existing key keeps row

    def _grow(self, need: int) -> None:
        cap = max(need, self._cap * 2)
        for field in ("_dead", "_p_node_row", "_p_has_node", "_p_active",
                      "_p_workload", "_p_tpu", "_p_tpu_chips",
                      "_p_gang", "_p_ns"):
            old = getattr(self, field)
            buf = np.zeros(cap, old.dtype)
            buf[:self._n] = old[:self._n]
            setattr(self, field, buf)
        for i, old in enumerate(self._p_axes):
            buf = np.zeros(cap, np.float64)
            buf[:self._n] = old[:self._n]
            self._p_axes[i] = buf
        self._cap = cap

    def _ensure_axis(self, axis: str) -> int:
        aid = self._axis_ids.get(axis)
        if aid is None:
            aid = len(self._axes)
            self._axis_ids[axis] = aid
            self._axes.append(axis)
            self._p_axes.append(np.zeros(self._cap, np.float64))
        return aid

    def _intern_gang(self, key: Any) -> int:
        gid = self._gang_ids.get(key)
        if gid is None:
            gid = len(self._gang_keys)
            self._gang_ids[key] = gid
            self._gang_keys.append(key)
        return gid

    def _intern_ns(self, ns: str) -> int:
        nid = self._ns_ids.get(ns)
        if nid is None:
            nid = len(self._ns_keys)
            self._ns_ids[ns] = nid
            self._ns_keys.append(ns)
        return nid

    def _write_row(self, row: int, p: Any) -> None:
        name = p.node_name or None
        self._p_has_node[row] = name is not None
        self._p_node_row[row] = (self._node_row_of.get(name, -1)
                                 if name else -1)
        self._p_active[row] = p.phase in _ACTIVE_PHASES
        self._p_workload[row] = p.is_workload
        self._p_tpu[row] = p.resources.get(TPU_RESOURCE)
        self._p_tpu_chips[row] = p.tpu_chips
        self._p_gang[row] = self._intern_gang(p.gang_key)
        self._p_ns[row] = self._intern_ns(p.namespace)
        for buf in self._p_axes:
            buf[row] = 0.0
        for axis, v in p.resources.as_dict().items():
            self._p_axes[self._ensure_axis(axis)][row] = v

    def _append_row(self, key: str, p: Any) -> None:
        row = self._n
        if row + 1 > self._cap:
            self._grow(row + 1)
        self._n = row + 1
        self._dead[row] = False
        name = p.node_name or None
        self._keys.append(key)
        self._sigs.append(pod_sig(p))
        self._names.append(name)
        if name:
            self._rows_by_nodename.setdefault(name, set()).add(row)
        self._row_of[key] = row
        self._write_row(row, p)

    def _update_row(self, row: int, p: Any) -> None:
        old_name = self._names[row]
        new_name = p.node_name or None
        if old_name != new_name:
            if old_name is not None:
                rows = self._rows_by_nodename.get(old_name)
                if rows is not None:
                    rows.discard(row)
                    if not rows:
                        del self._rows_by_nodename[old_name]
            if new_name is not None:
                self._rows_by_nodename.setdefault(new_name,
                                                  set()).add(row)
        self._names[row] = new_name
        self._sigs[row] = pod_sig(p)
        self._write_row(row, p)

    def _kill_row(self, key: str, row: int) -> None:
        self._dead[row] = True
        self._dead_count += 1
        del self._row_of[key]
        name = self._names[row]
        if name is not None:
            rows = self._rows_by_nodename.get(name)
            if rows is not None:
                rows.discard(row)
                if not rows:
                    del self._rows_by_nodename[name]
            self._names[row] = None

    def _compact(self) -> None:
        """Squeeze dead rows out (live relative order — and therefore
        the exported value — is unchanged; only row numbers shift)."""
        live = np.flatnonzero(~self._dead[:self._n])
        n = len(live)
        cap = max(_MIN_CAP, n)
        for field in ("_p_node_row", "_p_has_node", "_p_active",
                      "_p_workload", "_p_tpu", "_p_tpu_chips",
                      "_p_gang", "_p_ns"):
            old = getattr(self, field)
            buf = np.zeros(cap, old.dtype)
            buf[:n] = old[live]
            setattr(self, field, buf)
        axes = []
        for old in self._p_axes:
            buf = np.zeros(cap, np.float64)
            buf[:n] = old[live]
            axes.append(buf)
        self._p_axes = axes
        self._keys = [self._keys[i] for i in live]
        self._sigs = [self._sigs[i] for i in live]
        self._names = [self._names[i] for i in live]
        self._row_of = {k: i for i, k in enumerate(self._keys)}
        self._rows_by_nodename = {}
        for i, name in enumerate(self._names):
            if name is not None:
                self._rows_by_nodename.setdefault(name, set()).add(i)
        self._dead = np.zeros(cap, bool)
        self._dead_count = 0
        self._n = n
        self._cap = cap

    # -- export ------------------------------------------------------------

    def _export_state(self) -> ColumnarState:
        if self._export is not None:
            return self._export
        if self._dead_count:
            live = np.flatnonzero(~self._dead[:self._n])
        else:
            live = np.arange(self._n)
        first_sig = last_sig = None
        if len(live):
            first_sig = self._sigs[int(live[0])]
            last_sig = self._sigs[int(live[-1])]
        state = ColumnarState(
            templates=self.templates,
            # Node arrays are replaced (never mutated in place) on
            # rebuild, so sharing them with the export is safe; pod
            # columns are gathered copies of the live rows.
            nodes=self._nodes,
            n_ready=self._n_ready, n_sched=self._n_sched,
            n_is_tpu=self._n_is_tpu, n_chips=self._n_chips,
            n_tmpl=self._n_tmpl,
            slice_gid=self._slice_gid, unit_gid=self._unit_gid,
            slices=self._slices, units=self._units,
            n_pods=len(live),
            p_node_row=self._p_node_row[live],
            p_has_node=self._p_has_node[live],
            p_active=self._p_active[live],
            p_workload=self._p_workload[live],
            p_tpu=self._p_tpu[live],
            p_tpu_chips=self._p_tpu_chips[live],
            p_gang=self._p_gang[live],
            p_ns=self._p_ns[live],
            # Gang/ns interns are grow-only, so sharing is safe; the
            # axis LIST is copied because p_axes' length must stay
            # equal to it even if the view later learns a new axis.
            gang_keys=self._gang_keys, gang_ids=self._gang_ids,
            ns_keys=self._ns_keys, ns_ids=self._ns_ids,
            axes=list(self._axes), axis_ids=dict(self._axis_ids),
            p_axes=[buf[live] for buf in self._p_axes],
            node_digest=self._node_digest,
            pod_digest=self._pod_digest,
            first_pod_sig=first_sig, last_pod_sig=last_sig)
        self._export = state
        return state
