"""Pod and Node value objects over raw Kubernetes API payloads.

Analog of the reference's kube.py §KubePod / §KubeNode, with three deliberate
changes for the TPU-native design:

- Built from plain dicts (JSON payloads), not pykube objects, so every test
  layer constructs fixtures directly (SURVEY.md §5) and the same objects work
  against the real apiserver and the in-memory fake.
- Nodes know their *slice membership* (``autoscaler.tpu.dev/slice-id`` /
  GKE node-pool label): the unit of scale-down is the slice, never the node.
- Drain uses the eviction API (the reference predates it and raw-deleted
  pods; kube.py §KubeNode.drain).
"""

from __future__ import annotations

import collections
import datetime
import functools
import threading
from typing import TYPE_CHECKING, Any, Mapping, Sequence, TypeVar

from tpu_autoscaler.k8s.resources import ResourceVector
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    INSTANCE_TYPE_LABEL,
    POOL_LABEL,
    SLICE_ID_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
)

if TYPE_CHECKING:
    from tpu_autoscaler.k8s.client import KubeClient

# Legacy instance-type label still seen on older nodes (and the one the
# reference keyed on: kube.py §KubeNode.instance_type).
_LEGACY_INSTANCE_TYPE_LABEL = "beta.kubernetes.io/instance-type"

# Pods that opt out of autoscaler-driven eviction, same contract as the
# upstream cluster-autoscaler uses.
SAFE_TO_EVICT_ANNOTATION = "cluster-autoscaler.kubernetes.io/safe-to-evict"
MIRROR_ANNOTATION = "kubernetes.io/config.mirror"

# Stamped on pods the autoscaler cannot (currently) serve — planner
# verdicts (no catalog shape / clamp exceeded) and failed-provision
# causes (actuators/errors.py taxonomy).  Lives here, not in the
# reconciler, so read-only consumers (controller/status.py) don't pull
# the whole control loop in for one string.
UNSATISFIABLE_ANNOTATION = "autoscaler.tpu.dev/unsatisfiable"

# Gang-identity labels (JobSet / Job machinery).
JOBSET_NAME_LABEL = "jobset.sigs.k8s.io/jobset-name"
JOBSET_JOB_INDEX_LABEL = "jobset.sigs.k8s.io/job-index"
JOB_NAME_LABEL = "batch.kubernetes.io/job-name"
_LEGACY_JOB_NAME_LABEL = "job-name"


def parse_time(value: str | None) -> datetime.datetime | None:
    """Parse an RFC3339 timestamp as emitted by the Kubernetes API."""
    if not value:
        return None
    return datetime.datetime.fromisoformat(value.replace("Z", "+00:00"))


def _memoized(fn):
    """Lock-free per-instance memo for the immutable parsed views.

    NOT ``functools.cached_property``: on Python <= 3.11 that guards
    every cache miss with ONE re-entrant lock per descriptor shared
    across ALL instances, which would serialize the shard workers'
    cold-object first touches (ISSUE 13) on exactly the predicates
    they fan out over.  The benign lost-update race here recomputes an
    idempotent pure function — last write wins, same value.
    """
    attr = "_memo_" + fn.__name__

    @functools.wraps(fn)
    def wrapper(self):
        try:
            return getattr(self, attr)
        except AttributeError:
            value = fn(self)
            setattr(self, attr, value)
            return value

    return property(wrapper)


class Pod:
    """One pod, read-only view plus delete/evict verbs."""

    def __init__(self, payload: Mapping[str, Any]) -> None:
        self._p = payload
        meta = payload.get("metadata", {})
        self.name: str = meta.get("name", "")
        self.namespace: str = meta.get("namespace", "default")
        self.uid: str = meta.get("uid", "")
        self.resource_version: str | None = meta.get("resourceVersion")
        self.labels: dict[str, str] = dict(meta.get("labels") or {})
        self.annotations: dict[str, str] = dict(meta.get("annotations") or {})
        self.created: datetime.datetime | None = parse_time(
            meta.get("creationTimestamp"))
        self._owners: list[dict[str, Any]] = meta.get(
            "ownerReferences") or []
        spec = payload.get("spec", {})
        self.node_name: str | None = spec.get("nodeName")
        self.node_selectors: dict[str, str] = dict(spec.get("nodeSelector") or {})
        self.tolerations: list[dict[str, Any]] = list(
            spec.get("tolerations") or [])
        self.priority_class: str | None = spec.get("priorityClassName")
        self.priority: int = int(spec.get("priority") or 0)
        # Hard scheduling constraints beyond node-local admission
        # (evaluated by k8s/scheduling.py in the fake scheduler and the
        # planner's CPU packing path).
        self.affinity: dict[str, Any] = dict(spec.get("affinity") or {})
        self.topology_spread: list[dict[str, Any]] = list(
            spec.get("topologySpreadConstraints") or [])
        self.resources: ResourceVector = self._sum_requests(spec)
        status = payload.get("status", {})
        self.phase: str = status.get("phase", "")
        self._conditions: list[dict[str, Any]] = status.get(
            "conditions") or []

    @staticmethod
    def _sum_requests(spec: Mapping[str, Any]) -> ResourceVector:
        """Effective pod request: sum(containers) ∨ max(initContainers).

        The reference summed container requests (kube.py §KubePod);
        Kubernetes' effective-request rule additionally lower-bounds by each
        init container, which matters for nothing TPU-specific but is the
        correct algebra.
        """
        total = ResourceVector({"pods": 1})
        for c in spec.get("containers") or []:
            total = total + ResourceVector.from_raw(
                (c.get("resources") or {}).get("requests")
            )
        for c in spec.get("initContainers") or []:
            init = ResourceVector.from_raw(
                (c.get("resources") or {}).get("requests")
            )
            # sorted(): the union iterates in hash order, and dict
            # insertion order survives into every serialization of the
            # vector — under a different PYTHONHASHSEED the offline
            # bundle replay would see different bytes (TAD904).
            bumped = {
                k: max(total.get(k), init.get(k))
                for k in sorted(set(total.as_dict()) | set(init.as_dict()))
            }
            total = ResourceVector(bumped)
        return total

    # -- classification (reference: kube.py §KubePod is_mirrored/is_replicated
    #    /is_critical) ------------------------------------------------------

    # Classification predicates are memoized, not plain properties: a
    # parsed Pod is an immutable view of ONE (uid, resourceVersion) —
    # any change arrives as a new object — and these predicates run
    # per unit per reconcile pass over every bound pod (state machine,
    # spare/claim accounting, drains), which made the repeated
    # ownerReferences/annotation walks a measurable slice of the
    # million-pod pass (ISSUE 13 audit).

    @_memoized
    def owner_kind(self) -> str | None:
        return self._owners[0].get("kind") if self._owners else None

    @_memoized
    def is_mirrored(self) -> bool:
        return MIRROR_ANNOTATION in self.annotations

    @_memoized
    def is_daemonset(self) -> bool:
        return self.owner_kind == "DaemonSet"

    @property
    def is_replicated(self) -> bool:
        return self.owner_kind in {"ReplicaSet", "ReplicationController",
                                   "StatefulSet", "Job", "JobSet"}

    @property
    def is_critical(self) -> bool:
        if self.priority_class in {"system-cluster-critical",
                                   "system-node-critical"}:
            return True
        return self.annotations.get(SAFE_TO_EVICT_ANNOTATION) == "false"

    @_memoized
    def is_drainable(self) -> bool:
        """Evictable during a drain: replicated, not mirror/DS/critical."""
        return (self.is_replicated and not self.is_mirrored
                and not self.is_daemonset and not self.is_critical)

    @_memoized
    def is_workload(self) -> bool:
        """Counts toward a unit being busy: an active pod that is not
        host-plumbing (daemonset/mirror).  THE busy/idle input predicate —
        shared by the state machine, spare accounting, drain completion,
        and preemption so they can never diverge."""
        return (not self.is_daemonset and not self.is_mirrored
                and self.phase in {"Pending", "Running"})

    # -- scheduling state (reference: cluster.py §get_pending_pods) ---------

    @property
    def is_scheduled(self) -> bool:
        return bool(self.node_name) and self.phase in {"Pending", "Running"}

    @property
    def is_unschedulable(self) -> bool:
        """Pending with the scheduler's Unschedulable verdict — the demand
        signal that drives scale-up."""
        if self.phase != "Pending" or self.node_name:
            return False
        for cond in self._conditions:
            if (cond.get("type") == "PodScheduled"
                    and cond.get("status") == "False"
                    and cond.get("reason") == "Unschedulable"):
                return True
        return False

    # -- TPU demand ---------------------------------------------------------

    @property
    def tpu_chips(self) -> int:
        return int(self.resources.get(TPU_RESOURCE))

    @property
    def requests_tpu(self) -> bool:
        return self.tpu_chips > 0

    @property
    def tpu_accelerator(self) -> str | None:
        return self.node_selectors.get(ACCELERATOR_LABEL)

    @property
    def tpu_topology(self) -> str | None:
        return self.node_selectors.get(TOPOLOGY_LABEL)

    # -- gang identity ------------------------------------------------------

    def tolerates(self, taint: Mapping[str, Any]) -> bool:
        """Kubernetes toleration matching for one taint."""
        for tol in self.tolerations:
            op = tol.get("operator", "Equal")
            key_match = (not tol.get("key")  # empty key + Exists: all
                         or tol.get("key") == taint.get("key"))
            value_match = (op == "Exists"
                           or tol.get("value", "") == taint.get("value", ""))
            effect_match = (not tol.get("effect")
                            or tol.get("effect") == taint.get("effect"))
            if key_match and value_match and effect_match:
                return True
        return False

    @_memoized
    def gang_key(self) -> tuple[str, str, str]:
        """Demand-unit identity: pods sharing a key are one gang.

        One Kubernetes Job == one gang == one slice (a JobSet's replicated
        jobs are separate gangs, one per slice; BASELINE config #4).  Solo
        pods are singleton gangs.
        """
        job = self.labels.get(JOB_NAME_LABEL) or self.labels.get(
            _LEGACY_JOB_NAME_LABEL)
        if job:
            return ("job", self.namespace, job)
        jobset = self.labels.get(JOBSET_NAME_LABEL)
        if jobset:
            idx = self.labels.get(JOBSET_JOB_INDEX_LABEL, "0")
            return ("jobset", self.namespace, f"{jobset}/{idx}")
        return ("pod", self.namespace, self.name)

    @property
    def jobset_name(self) -> str | None:
        return self.labels.get(JOBSET_NAME_LABEL)

    # -- verbs --------------------------------------------------------------

    def evict(self, client: "KubeClient") -> None:
        client.evict_pod(self.namespace, self.name)

    def delete(self, client: "KubeClient") -> None:
        client.delete_pod(self.namespace, self.name)

    def __repr__(self) -> str:
        return f"Pod({self.namespace}/{self.name}, phase={self.phase})"


class Node:
    """One node, read-only view plus cordon/uncordon/drain verbs."""

    def __init__(self, payload: Mapping[str, Any]) -> None:
        self._p = payload
        meta = payload.get("metadata", {})
        self.name: str = meta.get("name", "")
        self.uid: str = meta.get("uid", "")
        self.resource_version: str | None = meta.get("resourceVersion")
        self.labels: dict[str, str] = dict(meta.get("labels") or {})
        self.annotations: dict[str, str] = dict(meta.get("annotations") or {})
        self.created: datetime.datetime | None = parse_time(
            meta.get("creationTimestamp"))
        spec = payload.get("spec", {})
        self.unschedulable: bool = bool(spec.get("unschedulable", False))
        self.taints: list[dict[str, Any]] = list(spec.get("taints") or [])
        status = payload.get("status", {})
        self.allocatable: ResourceVector = ResourceVector.from_raw(
            status.get("allocatable") or status.get("capacity"))
        self._conditions: list[dict[str, Any]] = status.get(
            "conditions") or []

    @property
    def instance_type(self) -> str | None:
        """Machine type from the well-known label (reference: kube.py
        §KubeNode.instance_type via beta.kubernetes.io/instance-type)."""
        return (self.labels.get(INSTANCE_TYPE_LABEL)
                or self.labels.get(_LEGACY_INSTANCE_TYPE_LABEL))

    @property
    def is_ready(self) -> bool:
        for cond in self._conditions:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    # -- slice / pool membership -------------------------------------------

    @property
    def slice_id(self) -> str | None:
        """Identity of the ICI slice this host belongs to.

        Preference order: our explicit slice label, then the GKE node-pool
        label (one multi-host TPU node pool == one slice in GKE semantics).
        CPU nodes in autoscaler-managed pools also carry the pool label but
        each is its own unit; the state layer handles that distinction.
        """
        return (self.labels.get(SLICE_ID_LABEL)
                or self.labels.get("cloud.google.com/gke-nodepool"))

    @property
    def pool(self) -> str | None:
        return self.labels.get(POOL_LABEL)

    @_memoized
    def is_tpu(self) -> bool:
        # Memoized like the Pod predicates: a parsed Node is an
        # immutable (uid, resourceVersion) view, and this runs per
        # node per pass in unit grouping and supply partitioning.
        return self.allocatable.get(TPU_RESOURCE) > 0

    @property
    def tpu_accelerator(self) -> str | None:
        return self.labels.get(ACCELERATOR_LABEL)

    @property
    def tpu_topology(self) -> str | None:
        return self.labels.get(TOPOLOGY_LABEL)

    # -- fit + selector matching (reference: kube.py §KubeNode.can_fit /
    #    .is_match) ---------------------------------------------------------

    def can_fit(self, request: ResourceVector) -> bool:
        return request.fits_in(self.allocatable)

    def matches_selectors(self, selectors: Mapping[str, str]) -> bool:
        # Plain loop, not all(genexpr): this runs O(pods x nodes) per
        # scheduler/planner pass and the generator frame was visible in
        # the controller-overhead profile.
        labels = self.labels
        for k, v in selectors.items():
            if labels.get(k) != v:
                return False
        return True

    def admits(self, pod: Pod) -> bool:
        """Selector match + every NoSchedule/NoExecute taint tolerated.

        GKE TPU node pools carry ``google.com/tpu=present:NoSchedule``; a
        workload without the toleration can never land there, so the fit
        engine must not count such supply for it (and vice versa: the fake
        scheduler must not bind it).
        """
        if not self.matches_selectors(pod.node_selectors):
            return False
        for t in self.taints:
            if t.get("effect") in ("NoSchedule", "NoExecute") \
                    and not pod.tolerates(t):
                return False
        return True

    # -- verbs --------------------------------------------------------------

    def cordon(self, client: "KubeClient") -> None:
        client.patch_node(self.name, {"spec": {"unschedulable": True}})

    def uncordon(self, client: "KubeClient") -> None:
        client.patch_node(self.name, {"spec": {"unschedulable": False}})

    def drain(self, client: "KubeClient", pods: Sequence[Pod]) -> int:
        """Evict all drainable pods on this node; returns count evicted.

        Mirror/daemonset/critical pods are skipped, as in the reference
        (kube.py §KubeNode.drain), but via the eviction API so
        PodDisruptionBudgets are honored by the apiserver.
        """
        import logging

        evicted = 0
        for pod in pods:
            if pod.node_name == self.name and pod.is_drainable:
                try:
                    pod.evict(client)
                    evicted += 1
                except Exception:  # crash-only: 429 from a PDB is the
                    # eviction API working as designed; other pods (and
                    # other units) must still drain, retried next pass.
                    logging.getLogger(__name__).warning(
                        "eviction of %s/%s blocked (PDB?); will retry",
                        pod.namespace, pod.name, exc_info=True)
        return evicted

    def delete(self, client: "KubeClient") -> None:
        client.delete_node(self.name)

    def __repr__(self) -> str:
        return f"Node({self.name}, type={self.instance_type})"


# ---- memoized parsing --------------------------------------------------
#
# The informer observe path (k8s/informer.py) hands the reconciler the
# same payloads pass after pass; re-running the ``Pod``/``Node``
# constructors on unchanged objects was the dominant per-pass cost at
# cluster scale (ISSUE 2).  The apiserver guarantees that
# (uid, resourceVersion) identifies one immutable version of an object,
# so parsing is memoized on exactly that key.  Payloads missing either
# field (hand-built test fixtures, fakes that don't track versions)
# parse fresh every time — memoization is a pure optimization and
# opting out is always correct.
#
# The caches are bounded LRU (eviction on insert) and guarded by one
# lock: the informer's watch threads parse at delta-apply time while
# the reconcile thread parses on its fallback/refresh LIST path.
#
# Sizing: a fixed bound thrashes at mega-cluster scale — with 100k pods
# in the informer store, a 16k memo evicts every entry before its next
# hit and silently turns into a miss machine.  The informer therefore
# reports its store size through ``reserve_parse_cache`` after every
# relist and the bound tracks 2x the largest store seen (headroom for
# one full version churn), never below the floor.  Hit/miss counters
# feed the ``parse_cache_hit_rate`` gauge (docs/OPERATIONS.md).

_PARSE_CACHE_FLOOR = 16384

_T = TypeVar("_T", "Pod", "Node")

_parse_lock = threading.Lock()
_pod_cache: collections.OrderedDict[tuple[str, str], Pod] = \
    collections.OrderedDict()
_node_cache: collections.OrderedDict[tuple[str, str], Node] = \
    collections.OrderedDict()
_parse_limits: dict[str, int] = {"pods": _PARSE_CACHE_FLOOR,
                                 "nodes": _PARSE_CACHE_FLOOR}
_parse_hits: dict[str, int] = {"pods": 0, "nodes": 0}
_parse_misses: dict[str, int] = {"pods": 0, "nodes": 0}


def reserve_parse_cache(kind: str, store_size: int) -> None:
    """Size ``kind``'s memo for a store of ``store_size`` objects.

    Called by the informer after a relist; the bound only ratchets up
    (2x the largest store seen, floor ``_PARSE_CACHE_FLOOR``) so a
    transiently small LIST can't shrink a warm cache.
    """
    want = max(_PARSE_CACHE_FLOOR, 2 * int(store_size))
    with _parse_lock:
        if want > _parse_limits.get(kind, 0):
            _parse_limits[kind] = want


def _parse_memoized(kind: str,
                    cache: collections.OrderedDict[tuple[str, str], _T],
                    cls: type[_T], payload: Mapping[str, Any]) -> _T:
    meta = payload.get("metadata") or {}
    uid = meta.get("uid")
    rv = meta.get("resourceVersion")
    if not uid or not rv:
        return cls(payload)
    key = (uid, rv)
    with _parse_lock:
        hit = cache.get(key)
        if hit is not None:
            _parse_hits[kind] += 1
            cache.move_to_end(key)
            return hit
        _parse_misses[kind] += 1
    obj = cls(payload)
    with _parse_lock:
        cache[key] = obj
        cache.move_to_end(key)
        limit = _parse_limits[kind]
        while len(cache) > limit:
            cache.popitem(last=False)
    return obj


def parse_pod(payload: Mapping[str, Any]) -> Pod:
    """Dict → ``Pod``, memoized on (uid, resourceVersion)."""
    return _parse_memoized("pods", _pod_cache, Pod, payload)


def parse_node(payload: Mapping[str, Any]) -> Node:
    """Dict → ``Node``, memoized on (uid, resourceVersion)."""
    return _parse_memoized("nodes", _node_cache, Node, payload)


def parse_cache_info() -> dict[str, float]:
    """Cache sizes, limits, and hit/miss counts (tests, the observe
    bench, and the informer's ``parse_cache_hit_rate`` gauge)."""
    with _parse_lock:
        hits = _parse_hits["pods"] + _parse_hits["nodes"]
        misses = _parse_misses["pods"] + _parse_misses["nodes"]
        return {"pods": len(_pod_cache), "nodes": len(_node_cache),
                "pods_limit": _parse_limits["pods"],
                "nodes_limit": _parse_limits["nodes"],
                "hits": hits, "misses": misses,
                "hit_rate": hits / (hits + misses) if hits + misses else 0.0}


def clear_parse_caches() -> None:
    """Drop both memo caches and reset sizing/counters (test isolation)."""
    with _parse_lock:
        _pod_cache.clear()
        _node_cache.clear()
        for kind in ("pods", "nodes"):
            _parse_limits[kind] = _PARSE_CACHE_FLOOR
            _parse_hits[kind] = 0
            _parse_misses[kind] = 0
