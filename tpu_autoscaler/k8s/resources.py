"""Kubernetes resource-quantity algebra.

The heart of the fit math — analog of the reference's kube.py §KubeResource
(dict-vector with +, -, scalar *, and a "fits" comparison; SI/k8s unit
parsing m/Ki/Mi/Gi).  Extended for ``google.com/tpu`` chips, the TPU analog
of the reference's ``alpha.kubernetes.io/nvidia-gpu``.

Canonical units: cpu in cores (float), memory in bytes (float), extended
resources in counts (float).  All quantities are parsed per the Kubernetes
quantity grammar: decimal SI suffixes (k, M, G, T, P, E), binary suffixes
(Ki, Mi, Gi, Ti, Pi, Ei), milli suffix (m), and scientific notation.
"""

from __future__ import annotations

import functools
from typing import Iterator, Mapping

_BINARY_SUFFIXES = {
    "Ki": 1024.0,
    "Mi": 1024.0**2,
    "Gi": 1024.0**3,
    "Ti": 1024.0**4,
    "Pi": 1024.0**5,
    "Ei": 1024.0**6,
}
_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}


def parse_quantity(value: str | int | float) -> float:
    """Parse one Kubernetes quantity ('100m', '2', '128Mi', '1e3') to float.

    Memoized for strings: every reconcile pass re-wraps hundreds of
    pod/node payloads whose quantities are drawn from a tiny set of
    distinct strings ('2', '8', '110', '128Mi', ...), and this parser
    dominated the controller-overhead profile before the cache.  The
    function is pure, so the cache is semantics-free.
    """
    if isinstance(value, (int, float)):
        return float(value)
    return _parse_quantity_str(value)


@functools.lru_cache(maxsize=4096)
def _parse_quantity_str(value: str) -> float:
    s = value.strip()
    if not s:
        raise ValueError("empty quantity")
    if any(c.isspace() for c in s):
        # float() tolerates e.g. "1 " after suffix stripping; the k8s
        # grammar has no internal whitespace — reject typos loudly.
        raise ValueError(f"whitespace inside quantity {value!r}")
    for suffix, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    # 'E' doubles as the exponent marker ('1E3'); only treat it as the exa
    # suffix when what precedes it is a complete number on its own.
    if s[-1] in _DECIMAL_SUFFIXES:
        head = s[:-1]
        try:
            return float(head) * _DECIMAL_SUFFIXES[s[-1]]
        except ValueError:
            pass  # fall through: e.g. '1E3' -> float('1E3')
    return float(s)


class ResourceVector:
    """Immutable-ish resource vector with elementwise arithmetic.

    Keys are k8s resource names ('cpu', 'memory', 'pods', 'google.com/tpu',
    ...); missing keys read as 0.  Comparison semantics follow the
    reference's KubeResource: ``a.fits_in(b)`` iff every requested amount in
    ``a`` is <= the corresponding capacity in ``b``.
    """

    __slots__ = ("_r",)

    def __init__(self, raw: Mapping[str, str | int | float] | None = None, **kw):
        merged: dict[str, float] = {}
        for src in (raw or {}), kw:
            for k, v in src.items():
                merged[k] = merged.get(k, 0.0) + parse_quantity(v)
        # Zero entries are dropped so equality/emptiness are canonical.
        self._r = {k: v for k, v in merged.items() if v != 0.0}

    @classmethod
    def from_raw(cls, raw: Mapping[str, object] | None) -> "ResourceVector":
        return cls(raw or {})  # type: ignore[arg-type]

    def get(self, key: str) -> float:
        return self._r.get(key, 0.0)

    def keys(self) -> Iterator[str]:
        return iter(self._r.keys())

    def as_dict(self) -> dict[str, float]:
        return dict(self._r)

    @property
    def empty(self) -> bool:
        return not any(v > 0 for v in self._r.values())

    def __add__(self, other: "ResourceVector") -> "ResourceVector":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0.0) + v
        return ResourceVector(out)

    def __sub__(self, other: "ResourceVector") -> "ResourceVector":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0.0) - v
        return ResourceVector(out)

    def __mul__(self, scalar: float) -> "ResourceVector":
        return ResourceVector({k: v * scalar for k, v in self._r.items()})

    __rmul__ = __mul__

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, ResourceVector):
            return NotImplemented
        return self._r == other._r

    def __hash__(self) -> int:
        return hash(frozenset(self._r.items()))

    def fits_in(self, capacity: "ResourceVector") -> bool:
        """True iff this request fits inside ``capacity`` on every axis.

        A positive request for a resource the capacity lacks entirely (e.g.
        google.com/tpu on a CPU node) does not fit — this is how TPU pods
        are excluded from CPU pools without any special-casing.

        Plain loop, not all(genexpr): this is the innermost comparison
        of every scheduler/planner fit pass (profiled hot).
        """
        cap = capacity._r
        for k, v in self._r.items():
            if v > 0 and v > cap.get(k, 0.0):
                return False
        return True

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v:g}" for k, v in sorted(self._r.items()))
        return f"ResourceVector({inner})"
