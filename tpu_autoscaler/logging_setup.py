"""Structured logging (SURVEY.md §6.1: the reference had only plain
``logging`` with -v/--debug; the rebuild's north star is a latency, so logs
must be machine-parsable for the detection→actuation trail).

JSON mode stamps every record with the active trace context
(``trace_id`` + ``span``, from ``tpu_autoscaler.obs.trace``): a log line
emitted while a gang's dispatch span is current carries that gang's
scale-up trace id, so `grep <trace_id>` over the log stream and
`tpu-autoscaler trace <trace_id>` over the flight recorder tell the
same story (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import logging
import sys

from tpu_autoscaler.obs.trace import current_span


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg (+exc, +trace)."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        span = current_span()
        if span is not None:
            entry["trace_id"] = span.trace_id
            entry["span"] = span.name
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry)


def setup_logging(verbose: bool = False, json_format: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(logging.DEBUG if verbose else logging.INFO)
