"""Structured logging (SURVEY.md §6.1: the reference had only plain
``logging`` with -v/--debug; the rebuild's north star is a latency, so logs
must be machine-parsable for the detection→actuation trail)."""

from __future__ import annotations

import json
import logging
import sys


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg (+exc)."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry)


def setup_logging(verbose: bool = False, json_format: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(logging.DEBUG if verbose else logging.INFO)
