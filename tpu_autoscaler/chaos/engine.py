"""Scenario executor: drive a program through the real control loop.

One run wires the production Controller (+ ClusterInformer in threadless
pump mode when the program says so) against a FakeKube behind a
brownout-injecting proxy and a FakeActuator with its seedable fault
knobs, then steps simulated time exactly like ``sim.py``: events fire,
the world is GC'd/recreated (a minimal Job-controller model), one
reconcile pass runs crash-only, the toy scheduler binds, and the
invariant monitor (``chaos/invariants.py``) checks every step.

Two drive modes:

- ``pump`` (default) — threadless, one deterministic interleaving;
  fast enough for the 200-seed CI corpus;
- ``sched`` — the same scenario under ``testing/sched.py``'s
  DeterministicScheduler with REAL informer watch threads, sweeping
  seeded interleavings; the expensive smoke tier.
"""

from __future__ import annotations

import dataclasses
import logging
import time as _time

from tpu_autoscaler.actuators.fake import FakeActuator
from tpu_autoscaler.chaos.invariants import SLICE_LABEL, InvariantMonitor
from tpu_autoscaler.chaos.scenario import (
    ScenarioProgram,
    Workload,
    generate,
)
from tpu_autoscaler.controller import Controller, ControllerConfig
from tpu_autoscaler.engine.planner import PoolPolicy
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.sim import gang_pods

log = logging.getLogger(__name__)

#: Verbs the brownout proxy fails — every apiserver read/write the
#: controller or informer performs (fixture mutators stay reachable:
#: the engine injects through the inner FakeKube directly).
_BROWNOUT_VERBS = frozenset({
    "list_pods", "list_nodes", "list_pods_raw", "list_nodes_raw",
    "patch_pod", "patch_node", "evict_pod", "delete_pod", "delete_node",
    "create_event", "watch_pods", "watch_nodes", "get_lease", "put_lease",
})


class BrownoutKube:
    """KubeClient proxy: every verb raises while a brownout window is
    open (sim-clocked via ``set_now``).  The controller must degrade —
    crash-only pass, informer unsync + relist — and converge after."""

    def __init__(self, inner: FakeKube) -> None:
        self._inner = inner
        self._windows: list[tuple[float, float]] = []
        self._now = 0.0

    def set_now(self, now: float) -> None:
        self._now = now

    def add_window(self, start: float, end: float) -> None:
        self._windows.append((start, end))

    def in_brownout(self, now: float | None = None) -> bool:
        now = self._now if now is None else now
        return any(start <= now < end for start, end in self._windows)

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in _BROWNOUT_VERBS and callable(attr):
            def guarded(*args, **kwargs):
                if self.in_brownout():
                    raise RuntimeError(
                        "chaos: apiserver brownout (503 service "
                        "unavailable)")
                return attr(*args, **kwargs)

            return guarded
        return attr


@dataclasses.dataclass
class ChaosResult:
    seed: int
    ok: bool
    violations: list[str]
    passes: int
    converged_at: float | None
    description: str
    wall_seconds: float
    reconcile_errors: int
    repairs: int
    repacks: int = 0
    #: ISSUE 17: ``columnar_plan_mismatches`` counter at scenario end —
    #: nonzero under ``--verify-columnar`` is a failure (the columnar
    #: fast path must be byte-identical to the Python oracle).
    columnar_mismatches: int = 0

    def describe(self) -> str:
        status = "ok" if self.ok else "FAIL"
        tail = "" if self.ok else (
            "\n  " + "\n  ".join(self.violations[:5]))
        conv = (f"converged@{self.converged_at:g}s"
                if self.converged_at is not None else "never converged")
        rp = f", {self.repacks} repacks" if self.repacks else ""
        return (f"[{status}] {self.description} — {conv}, "
                f"{self.passes} passes, {self.repairs} repairs{rp}, "
                f"{self.reconcile_errors} brownout-pass errors, "
                f"{self.wall_seconds:.2f}s wall{tail}")


#: ISSUE 9: serving scale-out records may hold adopted slices through
#: their TTL after traffic dies; widen the stranded-chips reclaim
#: window by exactly that bound when the serving profile is on.
SERVING_RECLAIM_ALLOWANCE = 240.0


class _ServingFuzz:
    """Fuzzed serving fleet feeding the metrics adapter (ISSUE 9).

    A handful of synthetic replicas advance real
    :class:`ServingStatsRecorder` rings every step under a seeded load
    profile; the fuzz then delivers their snapshots ADVERSARIALLY —
    restarts mid-window (fresh epoch, counters from zero), raw counter
    resets with an unchanged epoch (buggy exporter), stale and
    out-of-order re-deliveries, replica add/remove churn — and asserts
    the adapter's step invariants after every reconcile pass:

    - every pool signal's rates/gauges are finite and >= 0 (counter
      resets must NEVER yield a negative rate);
    - the incremental pool sums match a from-scratch rebuild
      (CapacityView-consistency, tests/test_serving_adapter.py's
      property at corpus scale).

    ISSUE 14 extends the fuzz to the request-trace samplers: every
    replica owns a :class:`RequestTraceSampler` driven through its
    full event API (submit/admit/seeded/preempt/finish) by a seeded
    synthetic request stream; a ``slow_decode`` event inflates one
    replica's decode durations past the SLO bound so tail capture
    fires mid-fault.  Step invariants:

    - every promoted request trace has a GAP-FREE span tree
      (``obs.trace_gaps`` — additive over the trace catalog);
    - sampler memory stays bounded (pending tracking and retained
      rings) through ``replica_restart`` / ``counter_reset`` / churn.

    Load ramps to zero before the quiet tail so the ServingScaler's
    advisory demand drains and convergence stays decidable.
    """

    #: Sampler bounds: small enough that the per-step gap check stays
    #: cheap at corpus scale, large enough to hold a fault window.
    TRACE_BOUNDS = dict(max_traces=24, max_pending=64, max_events=32)

    def __init__(self, program: ScenarioProgram, adapter,
                 monitor: InvariantMonitor) -> None:
        import random

        from tpu_autoscaler.serving.stats import ServingStatsRecorder

        self._recorder_cls = ServingStatsRecorder
        self.adapter = adapter
        self.monitor = monitor
        self.rng = random.Random(program.seed ^ 0x5E41)
        self.program = program
        self.pool = "chaos-web"
        self.shape = "v5e-4"
        self.accel = "tpu-v5-lite-device"
        self._replicas: dict[str, object] = {}
        self._samplers: dict[str, object] = {}
        #: Per-replica synthetic request stream state (tick counter,
        #: rid counter, open [rid, admitted] entries).
        self._req_state: dict[str, dict] = {}
        #: Trace ids already gap-checked (ids, not spans — bounded by
        #: the run's promotion count).
        self._validated: set[str] = set()
        self._last_spans_recorded: dict[str, int] = {}
        #: name -> (window end, factor) decode inflation (slow_decode).
        self._slow_until: dict[str, tuple[float, float]] = {}
        self._last_snap: dict[str, object] = {}
        self._seq = 0
        for _ in range(self.rng.randint(3, 6)):
            self._add_replica()
        #: Faults scheduled by the scenario, applied at their step.
        self._stale_budget = 0
        self._reset_next = False
        self._restart_next = False

    def _add_replica(self) -> None:
        self._seq += 1
        name = f"fuzz-rep-{self._seq}"
        self._attach_replica(name)

    def _attach_replica(self, name: str) -> None:
        """Fresh recorder + sampler pair (initial add AND restart —
        a restarted replica's sampler restarts with it; its old
        pending set must be dropped, not leaked)."""
        from tpu_autoscaler.serving.reqtrace import RequestTraceSampler

        rec = self._recorder_cls(slots=16, slo_ticks=4)
        self._replicas[name] = rec
        self._samplers[name] = RequestTraceSampler(
            name, sample_rate=0.1, slo_ticks=4, stats=rec,
            **self.TRACE_BOUNDS)
        self._req_state[name] = {"tick": 0, "n": 0, "open": []}
        self._last_spans_recorded[name] = 0

    def apply_event(self, event, t: float = 0.0) -> None:
        kind = event.kind
        if kind == "replica_restart":
            self._restart_next = True
        elif kind == "counter_reset":
            self._reset_next = True
        elif kind == "stale_burst":
            self._stale_budget += event.args["count"]
        elif kind == "slow_decode":
            # ISSUE 14: one replica's decode durations inflate for the
            # window — completions blow the SLO bound, the sampler's
            # tail capture must fire and stay gap-free/bounded.
            name = self.rng.choice(sorted(self._replicas))
            self._slow_until[name] = (t + event.args["duration"],
                                      event.args["factor"])
        elif kind == "replica_churn":
            for _ in range(event.args.get("add", 0)):
                self._add_replica()
            for _ in range(event.args.get("remove", 0)):
                if len(self._replicas) > 1:
                    name = self.rng.choice(sorted(self._replicas))
                    del self._replicas[name]
                    del self._samplers[name]
                    self._req_state.pop(name, None)
                    self._slow_until.pop(name, None)
                    self._last_spans_recorded.pop(name, None)
                    self._last_snap.pop(name, None)
                    self.adapter.remove(name)
        else:
            raise ValueError(f"unknown serving event kind {kind!r}")

    def _load(self, t: float) -> int:
        """Queued requests at t: a noisy pulse inside the driven
        phase, hard zero before the quiet tail."""
        from tpu_autoscaler.chaos.scenario import QUIET_TAIL

        if t >= self.program.until - QUIET_TAIL:
            return 0
        return self.rng.randint(0, 40)

    def step(self, t: float) -> None:
        """Advance every replica a few ticks and deliver snapshots,
        sometimes adversarially."""
        rng = self.rng
        if self._restart_next:
            self._restart_next = False
            name = rng.choice(sorted(self._replicas))
            # Mid-window restart: fresh recorder, fresh epoch — the
            # adapter must treat the zeroed counters as a reset, and
            # the sampler restarts WITH the replica (its pending set
            # dies with the process it mirrors).
            self._attach_replica(name)
        for name in sorted(self._replicas):
            rec = self._replicas[name]
            self._drive_requests(name, t)
            load = self._load(t)
            for _ in range(rng.randint(1, 3)):
                done = rng.randint(0, min(8, load + 4))
                for _ in range(done):
                    rec.note_finish(rng.randint(0, 8))
                rec.note_admit(done)
                rec.end_tick(
                    queue_depth=max(0, load - 16),
                    active=min(16, load),
                    kv_used=min(16, load) * 100, kv_capacity=4096,
                    decode_tokens_total=rec.finished_total * 100)
            if self._reset_next:
                self._reset_next = False
                # Raw counter reset, SAME epoch: a buggy exporter's
                # totals went backwards.  Rates must clamp, never go
                # negative.
                rec.finished_total = max(0, rec.finished_total - 200)
                rec.slo_ok_total = min(rec.slo_ok_total,
                                       rec.finished_total)
                rec._decode_tokens_total = rec.finished_total * 100
            snap = rec.snapshot()
            if self._stale_budget > 0 and name in self._last_snap \
                    and rng.random() < 0.5:
                # Out-of-order / duplicate delivery: the OLD snapshot
                # arrives after the new one.
                self._stale_budget -= 1
                self.adapter.ingest(name, self.pool, self.accel,
                                    self.shape, snap, now=t)
                self.adapter.ingest(name, self.pool, self.accel,
                                    self.shape, self._last_snap[name],
                                    now=t)
            else:
                self.adapter.ingest(name, self.pool, self.accel,
                                    self.shape, snap, now=t)
            self._last_snap[name] = snap

    def _drive_requests(self, name: str, t: float) -> None:
        """Advance one replica's synthetic request stream through the
        sampler's full event API.  Decode durations inflate inside an
        open ``slow_decode`` window — those completions miss the SLO
        and must be tail-captured."""
        from tpu_autoscaler.chaos.scenario import QUIET_TAIL

        sampler = self._samplers.get(name)
        if sampler is None:
            return
        rng = self.rng
        st = self._req_state[name]
        tick = st["tick"]
        driven = t < self.program.until - QUIET_TAIL
        if driven:
            for _ in range(rng.randint(0, 3)):
                st["n"] += 1
                rid = f"q{st['n']}"
                sampler.note_submit(rid, tick)
                st["open"].append([rid, False])
        tick += rng.randint(1, 3)
        keep: list[list] = []
        # Force progress on the oldest entries once the backlog grows
        # (bounded open set; in the quiet tail everything completes so
        # sampler pending drains to zero before terminal).
        force = len(st["open"]) > 24 or not driven
        for ent in st["open"]:
            rid, admitted = ent
            if not admitted:
                sampler.note_admit(rid, tick)
                sampler.note_seeded(rid, tick)
                ent[1] = True
                keep.append(ent)
                continue
            r = rng.random()
            if driven and r < 0.1:
                sampler.note_preempt(rid, tick)
                sampler.note_admit(rid, tick + 1)
                sampler.note_seeded(rid, tick + 1)
                keep.append(ent)
            elif force or r < 0.6:
                dur = rng.randint(0, 3)
                slow = self._slow_until.get(name)
                if slow is not None and t < slow[0]:
                    dur = int(dur * slow[1]) + int(slow[1])
                sampler.note_finish(rid, tick + dur, tokens=dur + 1)
            else:
                keep.append(ent)
        st["open"] = keep
        st["tick"] = tick + 1

    def check_traces(self, t: float) -> None:
        """ISSUE 14 step invariants: every promoted request trace is
        gap-free (``trace_gaps`` — tail captures included by
        construction), and sampler memory stays bounded.  O(new
        traces) per step: replicas whose rings did not grow since the
        last check are skipped."""
        from tpu_autoscaler.obs import trace_gaps

        for name in sorted(self._samplers):
            sampler = self._samplers[name]
            if sampler.pending > sampler.max_pending:
                self.monitor._fail(
                    t, "reqtrace-bounded",
                    f"{name}: sampler pending {sampler.pending} "
                    f"exceeds max_pending {sampler.max_pending}")
            recorded = sampler.recorder._spans_recorded
            if recorded == self._last_spans_recorded.get(name):
                continue
            self._last_spans_recorded[name] = recorded
            dump = sampler.dump()
            for span in dump["spans"]:
                if span["name"] != "request" \
                        or span["trace_id"] in self._validated:
                    continue
                self._validated.add(span["trace_id"])
                for gap in trace_gaps(dump, span["trace_id"]):
                    self.monitor._fail(t, "reqtrace-gap-free", gap)

    def check(self, t: float) -> None:
        """Step invariants over the folded signals (the reconcile pass
        the controller just ran did the fold)."""
        import numpy as np

        self.check_traces(t)

        # RAW pool sums, not the clamped PoolSignal view (the export
        # clamps defensively; the invariant is that the fold never
        # NEEDED the clamp — beyond bounded float drift).
        sums = self.adapter._pool_sums
        if not np.isfinite(sums).all():
            self.monitor._fail(
                t, "serving-nonnegative-rates",
                "non-finite pool aggregate after resets/stale "
                "deliveries")
        elif sums.size and float(sums.min()) < -1e-6:
            self.monitor._fail(
                t, "serving-nonnegative-rates",
                f"negative pool aggregate {float(sums.min())} — a "
                f"counter reset leaked a negative rate")
        # Raw incremental sums vs a from-scratch rebuild: bounded
        # float drift only (RELATIVE to the aggregate magnitude —
        # add/subtract maintenance accumulates ~1 ulp per fold).
        drift = self.adapter.drift()
        scale = max(1.0, float(np.abs(sums).max())) if sums.size \
            else 1.0
        if drift > 1e-6 * scale:
            self.monitor._fail(
                t, "serving-fold-consistency",
                f"incremental pool sums drifted {drift} from rebuild "
                f"(scale {scale:g})")


class _RouterFuzz:
    """Routed request stream over the fuzzed serving fleet (ISSUE 18).

    A :class:`RouterCore` rides the SAME adapter the serving fuzz
    feeds adversarially — so routing decisions are made over epochs
    that bump mid-session (``replica_restart``), rates that reset
    mid-hedge (``counter_reset``), and rows that die with requests in
    flight (``replica_churn`` removals).  The driver keeps its own
    request ledger (rid -> assigned replica) and asserts the two
    router safety invariants the bench gates can't see:

    - **no lost requests** — every submitted rid reaches completion
      by terminal: orphans of a removed replica are re-homed through
      the typed :class:`DrainReceipt` migration path (absorb_drain),
      storm-wedged requests through exactly-once hedging, and the
      quiet tail force-drains everything else;
    - **no double completion** — ``router.complete`` returns True
      exactly once per rid; the driver re-calls it on a sample of
      completed rids every step and fails the seed if a duplicate is
      ever acknowledged.  ``maybe_hedge`` likewise must never fire a
      second hedge for an already-hedged rid.

    A ``hedge_storm`` wedges a victim subset of the fleet: the driver
    marks them draining (the stall signal ``maybe_hedge`` keys on)
    and freezes their completions for the window, making every
    outstanding request on them hedge-eligible at once.
    """

    #: Hedge budget in sim-seconds: two reconcile steps — storms run
    #: 15-60 s, so every storm window produces hedge-eligible mass.
    HEDGE_AFTER_S = 10.0

    def __init__(self, program: ScenarioProgram, fuzz: _ServingFuzz,
                 adapter, monitor: InvariantMonitor) -> None:
        import random

        from tpu_autoscaler.serving.router import (
            RouterConfig,
            RouterCore,
        )

        self.program = program
        self.fuzz = fuzz
        self.adapter = adapter
        self.monitor = monitor
        self.rng = random.Random(program.seed ^ 0x207712)
        self.router = RouterCore(adapter, RouterConfig(
            hedge_after_s=self.HEDGE_AFTER_S))
        # Router refreshes run between passes; binding the controller's
        # profiler charges them to the out-of-pass ledger (ISSUE 20) so
        # chaos exercises that path under fault load too.
        self.router.profiler = monitor._controller.profiler
        #: rid -> [replica, dispatched_at]
        self.ledger: dict[str, list] = {}
        self.submitted = 0
        self.completed = 0
        self.hedged: set[str] = set()
        self.migrated = 0
        #: Completed rids kept for duplicate-completion probes
        #: (bounded sample, not the whole history).
        self._done_sample: list[str] = []
        self._storm_until = 0.0
        self._wedged: set[str] = set()
        self._seq = 0

    def apply_event(self, event, t: float) -> None:
        if event.kind != "hedge_storm":
            raise ValueError(f"unknown router event kind {event.kind!r}")
        self._storm_until = max(self._storm_until,
                                t + event.args["duration"])
        # Wedge about half the fleet (always leaving at least one
        # replica routable): draining is the router's stall signal,
        # the frozen completions are the outage itself.
        names = sorted(self.fuzz._replicas)
        victims = names[:max(1, len(names) // 2)] \
            if len(names) > 1 else []
        for name in victims:
            if name not in self._wedged:
                self._wedged.add(name)
                self.router.mark_draining(name)

    def _unwedge(self) -> None:
        for name in sorted(self._wedged):
            self.router.clear_draining(name)
        self._wedged.clear()

    def _migrate_orphans(self, t: float) -> None:
        """Re-home requests whose replica left the fleet (death
        mid-request) through the typed DrainReceipt path."""
        from tpu_autoscaler.serving.drain import DrainReceipt

        live = self.fuzz._replicas
        by_dead: dict[str, list[str]] = {}
        for rid, (replica, _t0) in sorted(self.ledger.items()):
            if replica not in live:
                by_dead.setdefault(replica, []).append(rid)
        for replica, rids in sorted(by_dead.items()):
            receipt = DrainReceipt(
                served=0, unserved=len(rids), drained=False,
                elapsed_s=0.0, ticks=0, decode_tokens=0,
                request_latency_ticks=(), request_wait_ticks=(),
                request_exec_ticks=(), stats={}, replica=replica)
            moves = self.router.absorb_drain(receipt, t)
            for rid, d in zip(rids, moves):
                self.ledger[rid][0] = d.replica
                self.migrated += 1
            # Fewer moves than orphans only when the whole fleet is
            # unroutable this pass — the rest retry next step.

    def step(self, t: float) -> None:
        from tpu_autoscaler.chaos.scenario import QUIET_TAIL

        rng = self.rng
        router = self.router
        if self._wedged and t >= self._storm_until:
            self._unwedge()
        router.refresh(t)
        self._migrate_orphans(t)
        driven = t < self.program.until - QUIET_TAIL

        # Hedging sweep: every outstanding rid, every step — the
        # router itself must gate on age/stall/exactly-once.
        for rid in sorted(self.ledger):
            d = router.maybe_hedge(rid, t)
            if d is None:
                continue
            if rid in self.hedged:
                self.monitor._fail(
                    t, "router-hedge-exactly-once",
                    f"{rid} hedged a second time (to {d.replica})")
            self.hedged.add(rid)
            self.ledger[rid][0] = d.replica

        # New submissions (driven phase only): ~30% session-sticky.
        if driven:
            for _ in range(rng.randint(0, 5)):
                self._seq += 1
                rid = f"r{self._seq}"
                session = (f"sess-{rng.randint(0, 15)}"
                           if rng.random() < 0.3 else None)
                d = router.dispatch(t, session=session, rid=rid)
                if d is None:
                    # Legal only when nothing is routable (every
                    # replica dead or wedged).
                    routable = [n for n in self.fuzz._replicas
                                if n not in self._wedged]
                    if routable and self.adapter.row_of(
                            sorted(routable)[0]) >= 0:
                        self.monitor._fail(
                            t, "router-no-lost-requests",
                            f"dispatch refused {rid} with "
                            f"{len(routable)} routable replica(s)")
                    continue
                self.submitted += 1
                self.ledger[rid] = [d.replica, t]
                if d.replica in self._wedged:
                    self.monitor._fail(
                        t, "router-drain-respected",
                        f"{rid} routed to draining {d.replica}")

        # Completions: wedged replicas freeze (that's the storm);
        # everything else completes at a seeded rate, everything
        # force-drains in the quiet tail.
        for rid in sorted(self.ledger):
            replica, _t0 = self.ledger[rid]
            if replica in self._wedged and replica in self.fuzz._replicas:
                continue
            if driven and rng.random() > 0.5:
                continue
            if not router.complete(rid):
                self.monitor._fail(
                    t, "router-no-double-completion",
                    f"{rid} completion unacknowledged (lost track "
                    f"or already completed)")
            del self.ledger[rid]
            self.completed += 1
            if len(self._done_sample) < 32:
                self._done_sample.append(rid)

        # Duplicate-completion probes: a rid completed earlier must
        # NEVER be acknowledged again.
        if self._done_sample and rng.random() < 0.25:
            rid = rng.choice(self._done_sample)
            if rid not in self.ledger and router.complete(rid):
                self.monitor._fail(
                    t, "router-no-double-completion",
                    f"{rid} acknowledged a SECOND completion")

    def check_terminal(self, t: float) -> None:
        if self._wedged:
            self._unwedge()
        if self.ledger:
            sample = ", ".join(sorted(self.ledger)[:5])
            self.monitor._fail(
                t, "router-no-lost-requests",
                f"{len(self.ledger)} request(s) never completed "
                f"({sample}…)")
        if self.completed != self.submitted:
            self.monitor._fail(
                t, "router-no-double-completion",
                f"completed {self.completed} != submitted "
                f"{self.submitted}")


#: ISSUE 12: the repack profile pre-seeds idle SPOT slices at t=0 and
#: runs a longer idle threshold so they survive into the migration
#: window; migrations themselves hold capacity in repair-family
#: states.  Widen the stranded-chips reclaim window by this bound.
REPACK_RECLAIM_ALLOWANCE = 360.0


def _repack_config(program: ScenarioProgram):
    """Chaos-scale RepackConfig: dwell/cooldown short enough to fire
    inside a scenario, admission margin and horizon small enough that
    the spot_dry fault actually flips the guard to abort."""
    if not program.repack:
        return None
    from tpu_autoscaler.repack import RepackConfig

    return RepackConfig(
        max_concurrent_migrations=2,
        min_savings_ratio=2.0,
        savings_horizon_seconds=1800.0,
        budget_chip_seconds=50_000.0,
        budget_window_seconds=1800.0,
        drain_estimate_seconds=30.0,
        provision_estimate_seconds=program.provision_delay + 20.0,
        min_dwell_seconds=30.0,
        gang_cooldown_seconds=180.0)


#: Chaos-scale PolicyEngine hold/threshold bounds (ISSUE 8): the
#: reclaim window the no-stranded-chips invariant allows is widened by
#: exactly this allowance when the policy is on — a prewarm may sit
#: warm through its hold and then still owes the normal idle clock.
POLICY_RECLAIM_ALLOWANCE = 360.0


def _policy_engine(program: ScenarioProgram):
    """Chaos-scale PolicyEngine: aggressive enough to actually fire
    inside a short scenario (tiny SLO target, short holds), bounded so
    its mispredictions stay inside POLICY_RECLAIM_ALLOWANCE."""
    if not program.policy:
        return None
    from tpu_autoscaler.policy import PolicyConfig, PolicyEngine, SloPolicy

    return PolicyEngine(PolicyConfig(
        slo=SloPolicy(
            target_scaleup_seconds=5.0,  # reactive always "misses"
            min_confidence=0.55,
            provision_estimate_seconds=program.provision_delay + 20.0,
            lead_slack_seconds=15.0,
            prewarm_hold_seconds=60.0,
            waste_budget_chip_seconds=50_000.0,
            idle_floor_seconds=60.0,
            idle_ceiling_seconds=240.0),
        hw_bin_seconds=30.0, hw_season_bins=8))


#: The alerts profile's rule name (the gate's assertion target) and
#: its chaos-scale parameters: the production catalog's burn rule with
#: windows shrunk to scenario timescales.  slo_bound stays 360 s — the
#: north-star budget — with quiet-alphabet scale-ups bounded well
#: under it (provision_delay <= 60 s + a <= 60 s brownout stall).
ALERT_RULE = "scaleup-latency-burn"


def _alert_engine(program: ScenarioProgram):
    """Chaos-scale AlertEngine for the ``alerts`` profile: ONLY the
    burn rule, so the corpus verdict (fires on regression seeds,
    silent on quiet seeds) is about exactly one instrument.  Window
    and hysteresis timing comes from scenario.py's ALERTS_* constants
    — generate() derives the driven phase's resolution slack from the
    SAME numbers.  Other profiles keep the Controller's default
    engine (prod windows; evaluated, not asserted)."""
    if not program.alerts:
        return None
    from tpu_autoscaler.chaos import scenario as sc
    from tpu_autoscaler.obs import AlertEngine, AlertRule

    return AlertEngine((AlertRule(
        name=ALERT_RULE, metric="scale_up_latency_seconds",
        kind="burn_rate", slo_bound=360.0, objective=0.9,
        fast_window=sc.ALERTS_FAST_WINDOW,
        slow_window=sc.ALERTS_SLOW_WINDOW, burn_threshold=2.0,
        min_events=1, for_passes=sc.ALERTS_FOR_PASSES,
        clear_passes=sc.ALERTS_CLEAR_PASSES),))


def _serving_scaler(program: ScenarioProgram):
    """Chaos-scale ServingScaler over a fresh adapter: small fleet
    cap, short record TTLs (a scenario is minutes, not hours),
    forecasting off — the corpus fuzzes the ADAPTER path and the
    scale-out lifecycle, not the seasonal model."""
    if not program.serving:
        return None
    from tpu_autoscaler.serving.adapter import ServingMetricsAdapter
    from tpu_autoscaler.serving.scaler import (
        ServingPolicy,
        ServingScaler,
    )

    return ServingScaler(
        ServingMetricsAdapter(),
        ServingPolicy(
            max_replicas=4, min_replicas=0,
            scaleout_hold_seconds=60.0, replica_grace_seconds=30.0,
            scalein_hold_seconds=60.0, forecast=False))


def _build(program: ScenarioProgram, kube_for_controller, kube: FakeKube,
           informer, reconcile_shards: int = 0,
           verify_columnar: bool = False
           ) -> tuple[Controller, FakeActuator]:
    import random

    actuator = FakeActuator(
        kube, rng=random.Random(program.seed ^ 0x5EED),
        provision_delay=program.provision_delay,
        stagger_seconds=program.stagger_seconds)
    controller = Controller(
        kube_for_controller, actuator,
        ControllerConfig(
            # ISSUE 13: the corpus re-runs with the sharded planner
            # attached (shard_min_gangs=0 so even 1-gang passes
            # exercise the fan-out/merge path); the invariant catalog
            # must hold unchanged — sharded plans are byte-identical
            # to serial by contract.
            reconcile_shards=reconcile_shards, shard_min_gangs=0,
            # ISSUE 17: --verify-columnar runs the Python planner as a
            # property oracle next to the columnar fast path on every
            # pass; mismatches are counted and fail the scenario.
            verify_columnar_plans=verify_columnar,
            policy=PoolPolicy(spare_nodes=0,
                              max_total_chips=program.max_total_chips,
                              # ISSUE 11: spot-tier seeds provision
                              # preemptible capacity — the cost
                              # ledger's price-tier dimension under
                              # the full fault alphabet.
                              preemptible=program.preemptible),
            grace_seconds=30.0,
            # Repack seeds hold pre-seeded idle spot slices long
            # enough to be migration destinations (the reclaim-window
            # allowance widens the stranded check by the same bound).
            idle_threshold_seconds=(240.0 if program.repack
                                    else 120.0),
            drain_grace_seconds=20.0, provision_retry_seconds=30.0,
            # The alerts profile stalls provisions for up to ~480 s
            # (latency_regression windows); the stuck-provision
            # cancel must not race the injected latency or the
            # regression never yields a COMPLETED slow scale-up.
            provision_timeout_seconds=(900.0 if program.alerts
                                       else 150.0),
            unhealthy_timeout_seconds=120.0,
            slice_repair_after_seconds=30.0,
            enable_repack=program.repack,
            repack=_repack_config(program)),
        informer=informer,
        policy_engine=_policy_engine(program),
        serving_scaler=_serving_scaler(program),
        alert_engine=_alert_engine(program))
    return controller, actuator


class _Run:
    """One scenario execution (pump mode)."""

    def __init__(self, program: ScenarioProgram,
                 reconcile_shards: int = 0,
                 verify_columnar: bool = False):
        from tpu_autoscaler.k8s.objects import clear_parse_caches

        # Hermetic seeds: every FakeKube restarts uids/resourceVersions
        # from 1, so the process-global (uid, rv) parse memo would hand
        # one seed another seed's parsed objects.
        clear_parse_caches()
        self.program = program
        self.kube = FakeKube()
        self.proxy = BrownoutKube(self.kube)
        self.informer = None
        if program.informer:
            from tpu_autoscaler.k8s.informer import ClusterInformer

            self.informer = ClusterInformer(self.proxy, timeout_seconds=0)
        self.verify_columnar = verify_columnar
        self.controller, self.actuator = _build(
            program, self.proxy, self.kube, self.informer,
            reconcile_shards=reconcile_shards,
            verify_columnar=verify_columnar)
        self.monitor = InvariantMonitor(program.seed, self.kube,
                                        self.controller)
        # ISSUE 9: serving-profile scenarios drive a fuzzed replica
        # fleet into the controller's metrics adapter.
        self.serving_fuzz = None
        if self.controller.serving_scaler is not None:
            self.serving_fuzz = _ServingFuzz(
                program, self.controller.serving_scaler.adapter,
                self.monitor)
        # ISSUE 18: the router profile drives a routed request stream
        # over the same fuzzed fleet/adapter.
        self.router_fuzz = None
        if program.router and self.serving_fuzz is not None:
            self.router_fuzz = _RouterFuzz(
                program, self.serving_fuzz,
                self.controller.serving_scaler.adapter, self.monitor)
        #: ISSUE 12: idle SPOT slices materialized by ``spot_arrive``
        #: events — the repack profile's migration destinations (and
        #: the spot_dry event's victims while still workload-free).
        self._spot_units: list[str] = []
        #: member job name -> its pod names (a multislice jobset
        #: contributes one entry per member job — the ICI-integrity
        #: invariant holds per job/slice, the jobset spans DCN).
        self.live_jobs: dict[str, list[str]] = {}
        #: member job name -> launch spec, for Job-controller
        #: recreation of missing pods.
        self._job_spec: dict[str, dict] = {}
        #: pending recurring re-launches: (at, workload, run index).
        self._relaunches: list[tuple[float, Workload, int]] = []
        self.arrived: set[str] = set()
        self.passes = 0
        self.reconcile_errors = 0
        #: Open latency-regression window end (ISSUE 10 alerts
        #: profile); provisions stall until it closes.
        self._regression_until: float | None = None
        import random

        self.rng = random.Random(program.seed ^ 0xC0FFEE)

    # -- world model ------------------------------------------------------

    def _member_jobs(self, w: Workload, run: int) -> list[dict]:
        """Launch specs for one run of a workload: N member jobs for a
        multislice jobset, else one job.  Recurring workloads carry a
        run suffix from run 0 so every run shares one base name (what
        the recurring predictor mines)."""
        base = w.job if w.repeat == 0 else f"{w.job}-r{run}"
        if w.jobset_slices <= 1:
            return [{"job": base, "shape": w.shape, "pinned": w.pinned,
                     "jobset": None, "job_index": None,
                     "workload": w.job}]
        return [{"job": f"{base}-s{i}", "shape": w.shape,
                 "pinned": w.pinned, "jobset": base, "job_index": i,
                 "workload": w.job}
                for i in range(w.jobset_slices)]

    def _launch(self, w: Workload, run: int) -> None:
        for spec in self._member_jobs(w, run):
            names = []
            for payload in gang_pods(spec["shape"], spec["job"],
                                     jobset=spec["jobset"],
                                     job_index=spec["job_index"],
                                     pin_topology=spec["pinned"]):
                self.kube.add_pod(payload)
                names.append(payload["metadata"]["name"])
            self.live_jobs[spec["job"]] = names
            self._job_spec[spec["job"]] = spec

    def _arrivals(self, t: float) -> None:
        for w in self.program.workloads:
            if w.job in self.arrived or w.arrival > t:
                continue
            self.arrived.add(w.job)
            self._launch(w, 0)
        # Recurring re-launches whose gap elapsed (scheduled only
        # inside the driven phase — see _completions).
        due = [r for r in self._relaunches if r[0] <= t]
        if due:
            self._relaunches = [r for r in self._relaunches if r[0] > t]
            for _at, w, run in due:
                self._launch(w, run)

    def _workload_members(self, w: Workload) -> list[str]:
        return [job for job, spec in self._job_spec.items()
                if spec["workload"] == w.job and job in self.live_jobs]

    def _completions(self, t: float) -> None:
        for w in self.program.workloads:
            if w.completion_prob <= 0.0:
                continue
            members = self._workload_members(w)
            if not members:
                continue
            names = [n for job in members for n in self.live_jobs[job]]
            if all((self.kube.get_pod("default", n) or {}).get(
                    "status", {}).get("phase") == "Running"
                   for n in names) \
                    and self.rng.random() < w.completion_prob:
                for n in names:
                    self.kube.delete_pod("default", n)
                runs_done = 0
                for job in members:
                    spec = self._job_spec.pop(job)
                    del self.live_jobs[job]
                    # Run index of the completed run (suffix-free
                    # workloads are single-run).
                    if w.repeat > 0:
                        runs_done = int(
                            job[len(w.job) + 2:].split("-")[0] or 0)
                next_run = runs_done + 1
                relaunch_at = t + w.repeat_gap
                if next_run <= w.repeat \
                        and relaunch_at <= self.program.until:
                    # Scheduled strictly inside the driven phase so
                    # the quiet tail stays quiet (convergence remains
                    # a decidable property).
                    self._relaunches.append((relaunch_at, w, next_run))

    def _node_gc_and_job_controller(self, t: float) -> None:
        """Model the two cluster actors the fake lacks: node-lifecycle
        GC (pods bound to deleted nodes are removed) and the Job
        controller (missing members of a live job are recreated)."""
        node_names = {n["metadata"]["name"]
                      for n in self.kube.list_nodes()}
        for p in list(self.kube.list_pods()):
            bound = p["spec"].get("nodeName")
            if bound and bound not in node_names:
                self.kube.delete_pod(
                    p["metadata"].get("namespace", "default"),
                    p["metadata"]["name"])
        for job, names in self.live_jobs.items():
            missing = [n for n in names
                       if self.kube.get_pod("default", n) is None]
            if not missing:
                continue
            spec = self._job_spec[job]
            fresh = {p["metadata"]["name"]: p
                     for p in gang_pods(spec["shape"], job,
                                        jobset=spec["jobset"],
                                        job_index=spec["job_index"],
                                        pin_topology=spec["pinned"])}
            for n in missing:
                self.kube.add_pod(fresh[n])

    # -- fault events -----------------------------------------------------

    def _apply_event(self, event, t: float) -> None:
        kind = event.kind
        if kind == "brownout":
            self.proxy.add_window(t, t + event.args["duration"])
        elif kind == "watch_storm":
            # Burst of irrelevant churn: annotation patches on existing
            # pods flood the watch journal and the delta path.
            pods = self.kube.list_pods()
            for i in range(event.args["count"]):
                if not pods:
                    break
                p = self.rng.choice(pods)
                self.kube.patch_pod(
                    p["metadata"].get("namespace", "default"),
                    p["metadata"]["name"],
                    {"metadata": {"annotations": {
                        "chaos.tpu.dev/storm": str(i)}}})
        elif kind == "flood_410":
            self.kube.expire_watch_window()
        elif kind == "stockout":
            self.actuator.set_fail_window(t, t + event.args["duration"])
        elif kind == "mid_provision_stockout":
            self.actuator.fail_in_flight()
        elif kind == "preempt":
            unit = self._pick_busy_unit()
            if unit is not None:
                self.actuator.preempt_unit(unit)
        elif kind == "host_fail":
            victim = self._pick_victim_host()
            if victim is not None:
                if event.args["mode"] == "delete":
                    self.monitor.injected_deletes.add(victim)
                self.actuator.fail_host(victim, event.args["mode"])
        elif kind == "latency_regression":
            # Stall every provision until the window closes (the
            # window length IS the injected scale-up latency); _step
            # restores the program's delay at the window end.
            self._regression_until = t + event.args["duration"]
            self.actuator.set_provision_delay(1e9)
        elif kind == "spot_arrive":
            # The spot market frees up: an idle preemptible slice of
            # the named shape appears — the displacement the repacker
            # exists to exploit.
            from tpu_autoscaler.k8s.payloads import tpu_host_payload
            from tpu_autoscaler.topology.catalog import shape_by_name

            shape = shape_by_name(event.args["shape"])
            sid = f"chaos-spot-{len(self._spot_units)}-{shape.name}"
            for h in range(shape.hosts):
                self.kube.add_node(tpu_host_payload(
                    shape, sid, h, created_at=t,
                    pool=f"spot-{shape.name}", preemptible=True))
            self._spot_units.append(sid)
        elif kind == "spot_dry":
            # The spot market dries up: pre-seeded spot slices still
            # workload-free vanish (engine-injected, never mistaken
            # for controller deletes).  A migration mid-drain loses
            # its destination — the budget guard must abort it.
            bound = {p["spec"].get("nodeName")
                     for p in self.kube.list_pods()
                     if p["spec"].get("nodeName")}
            for sid in self._spot_units:
                hosts = [n["metadata"]["name"]
                         for n in self.kube.list_nodes()
                         if n["metadata"].get("labels", {}).get(
                             SLICE_LABEL) == sid]
                if not hosts or any(h in bound for h in hosts):
                    continue  # in use (or already gone): not "idle spot"
                for h in hosts:
                    self.monitor.injected_deletes.add(h)
                    self.kube.delete_node(h)
        elif kind == "gang_delete":
            # The job is deleted outright (operator kubectl delete)
            # — possibly mid-drain of a repack migration, which must
            # then close abandoned without leaking bookkeeping.
            live = sorted(self.live_jobs)
            if live:
                job = self.rng.choice(live)
                for name in self.live_jobs.pop(job):
                    self.kube.delete_pod("default", name)
                spec = self._job_spec.pop(job, None)
                if spec is not None:
                    self._relaunches = [
                        r for r in self._relaunches
                        if r[1].job != spec["workload"]]
        elif self.router_fuzz is not None and kind == "hedge_storm":
            self.router_fuzz.apply_event(event, t)
        elif self.serving_fuzz is not None and kind in (
                "replica_restart", "counter_reset", "stale_burst",
                "replica_churn", "slow_decode"):
            self.serving_fuzz.apply_event(event, t)
        else:
            raise ValueError(f"unknown chaos event kind {kind!r}")

    def _busy_slices(self) -> dict[str, list[str]]:
        used: set[str] = set()
        for p in self.kube.list_pods():
            if p["spec"].get("nodeName") \
                    and p["status"].get("phase") == "Running":
                used.add(p["spec"]["nodeName"])
        out: dict[str, list[str]] = {}
        for n in self.kube.list_nodes():
            labels = n["metadata"].get("labels", {})
            sid = labels.get(SLICE_LABEL)
            if sid and labels.get("cloud.google.com/gke-tpu-accelerator"):
                out.setdefault(sid, []).append(n["metadata"]["name"])
        return {sid: hosts for sid, hosts in out.items()
                if any(h in used for h in hosts)}

    def _pick_busy_unit(self) -> str | None:
        busy = sorted(self._busy_slices())
        return self.rng.choice(busy) if busy else None

    def _pick_victim_host(self) -> str | None:
        multi = {sid: hosts for sid, hosts in self._busy_slices().items()
                 if len(hosts) > 1}
        if not multi:
            return None
        sid = self.rng.choice(sorted(multi))
        return self.rng.choice(sorted(multi[sid]))

    # -- the loop ---------------------------------------------------------

    def _step(self, t: float, events, completions: bool = True) -> None:
        self.proxy.set_now(t)
        if self._regression_until is not None \
                and t >= self._regression_until:
            self.actuator.set_provision_delay(
                self.program.provision_delay)
            self._regression_until = None
        for event in events:
            self._apply_event(event, t)
        self._arrivals(t)
        self._node_gc_and_job_controller(t)
        if completions:
            self._completions(t)
        if self.informer is not None:
            self.informer.pump()
        if self.serving_fuzz is not None:
            self.serving_fuzz.step(t)
        self.monitor.before_pass()
        try:
            self.controller.reconcile_once(now=t)
        except Exception:  # noqa: BLE001 — crash-only, like run_forever
            self.controller.metrics.inc("reconcile_errors")
            self.reconcile_errors += 1
            if not self.proxy.in_brownout(t):
                log.exception("reconcile pass crashed outside a brownout")
                self.monitor._fail(t, "crash-only-loop",
                                   "reconcile pass raised outside any "
                                   "brownout window")
        self.passes += 1
        self.kube.schedule_step()
        self.monitor.after_pass(t)
        if self.serving_fuzz is not None:
            self.serving_fuzz.check(t)
        if self.router_fuzz is not None:
            # After the pass: the fold this reconcile ran is what the
            # router's refresh re-prices from.
            self.router_fuzz.step(t)

    def _check_alerts(self, t: float) -> None:
        """The ISSUE 10 alert gate, asserted at terminal: an injected
        scale-up-latency regression must have FIRED the burn-rate
        alert inside the driven phase (a bounded number of passes —
        hysteresis is ``for_passes`` evaluations past the first
        in-window miss) and RESOLVED once the fault window aged out of
        the burn windows; a quiet seed must never have fired it (the
        zero-false-positive half)."""
        engine = self.controller.alerts
        st = engine.state_of(ALERT_RULE)
        regression = any(e.kind == "latency_regression"
                         for e in self.program.events)
        if regression:
            if st.fired_count < 1:
                self.monitor._fail(
                    t, "alert-fires-on-regression",
                    "injected scale-up-latency regression never fired "
                    "the burn-rate alert")
                return
            if st.fired_at is not None \
                    and st.fired_at > self.program.until:
                self.monitor._fail(
                    t, "alert-fires-bounded",
                    f"burn-rate alert fired at t={st.fired_at:g}, "
                    f"after the driven phase ended "
                    f"({self.program.until:g})")
            if st.firing:
                self.monitor._fail(
                    t, "alert-resolves-after-fault",
                    "burn-rate alert still firing at terminal, past "
                    "the fault window")
        elif st.fired_count:
            self.monitor._fail(
                t, "alert-quiet-corpus-silent",
                f"burn-rate alert fired {st.fired_count}x on a quiet "
                f"seed (false positive)")

    def _check_repack(self, t: float) -> None:
        """The ISSUE 12 repack gate, asserted at terminal
        (docs/REPACK.md "The savings guarantee"):

        - **never-net-negative-savings** — a migration's downside is
          bounded by its own (guard-capped) migration cost.  A
          completed migration that nets negative can only exist when
          the scheduler landed the gang on non-destination supply (a
          *misfire* — the one actor the controller does not command);
          its loss must never exceed the migration cost (anything
          more means the savings algebra itself is broken), and every
          such close must be surfaced on ``repack_misfires``;
        - **guard-capped abort cost** — an aborted/abandoned
          migration's realized cost never exceeds 1.5x its projected
          savings (the guard fires the first pass projected cost
          crosses savings; the slack covers one pass of drift plus
          the post-verdict drain teardown).
        """
        dump = self.controller.recorder.dump(
            tracer=self.controller.tracer)
        misfire_traces = 0
        for span in dump["spans"]:
            if span["name"] != "repack" \
                    or span["parent_id"] is not None \
                    or span["end"] is None:
                continue
            attrs = span["attrs"]
            unit = attrs.get("unit", "?")
            if attrs.get("aborted") or attrs.get("error"):
                cost = attrs.get("migration_cost_chip_seconds", 0.0)
                cap = attrs.get("projected_saving_chip_seconds", 0.0)
                if cost > 1.5 * cap + 1e-6:
                    self.monitor._fail(
                        t, "repack-abort-cost-capped",
                        f"aborted migration of {unit} burned {cost:g} "
                        f"chip-s against {cap:g} projected savings — "
                        f"the budget guard fired too late")
            elif attrs.get("chip_seconds_saved", 0.0) < 0.0:
                misfire_traces += 1
                loss = -attrs["chip_seconds_saved"]
                cost = attrs.get("migration_cost_chip_seconds", 0.0)
                if loss > cost + 1e-6:
                    self.monitor._fail(
                        t, "repack-never-net-negative",
                        f"completed migration of {unit} lost {loss:g} "
                        f"chip-s, MORE than its {cost:g} migration "
                        f"cost — the savings algebra is broken, not "
                        f"just a scheduler misfire")
        snap = self.controller.metrics.snapshot()
        counted = int(snap["counters"].get("repack_misfires", 0))
        if misfire_traces != counted:
            self.monitor._fail(
                t, "repack-never-net-negative",
                f"{misfire_traces} net-negative completed migration "
                f"trace(s) but repack_misfires={counted} — a loss "
                f"went unsurfaced")

    def execute(self) -> ChaosResult:
        # A sharded controller owns a worker pool; a 200-seed corpus
        # building one controller per seed must not leak its threads.
        try:
            return self._execute()
        finally:
            self.controller.close()

    def _execute(self) -> ChaosResult:
        t0 = _time.perf_counter()
        program = self.program
        pending_events = list(program.events)
        t = 0.0
        converged_at = None
        # Driven phase: events fire on schedule.
        while t <= program.until:
            due = [e for e in pending_events if e.t <= t]
            pending_events = [e for e in pending_events if e.t > t]
            self._step(t, due)
            t += program.step
        # Settle phase: no new faults; run until converged or deadline.
        deadline = program.until + program.settle
        while t <= deadline:
            self._step(t, ())
            if self.monitor.check_converged(t, self.live_jobs):
                converged_at = t
                break
            t += program.step
        # Reclaim window: idle supply must drain to zero (the
        # no-stranded-chips property needs the idle/grace/drain clocks
        # to have run out).
        reclaim_window = (self.controller.config.idle_threshold_seconds
                          + self.controller.config.grace_seconds
                          + self.controller.config.drain_grace_seconds
                          + 4 * program.step)
        if program.policy:
            # A prewarmed slice may legitimately sit warm through its
            # hold window and a stretched idle threshold before the
            # normal reclaim clocks run — the allowance is part of the
            # policy profile's contract (docs/CHAOS.md).
            reclaim_window += POLICY_RECLAIM_ALLOWANCE
        if program.serving:
            # Serving scale-out records may hold an adopted slice
            # through their TTL after the fuzzed load dies.
            reclaim_window += SERVING_RECLAIM_ALLOWANCE
        if program.repack:
            # Pre-seeded spot slices ride a longer idle threshold, and
            # an aborted migration restarts its source's idle clocks.
            reclaim_window += REPACK_RECLAIM_ALLOWANCE
        if converged_at is not None:
            # Completions freeze here: a job finishing mid-reclaim
            # would reset the idle clocks the stranded check reads.
            end = t + reclaim_window + 4 * program.step
            while t <= end:
                self._step(t, (), completions=False)
                t += program.step
        self.monitor.check_terminal(
            t, self.live_jobs, converged=converged_at is not None,
            reclaim_window=reclaim_window)
        if self.program.alerts:
            self._check_alerts(t)
        if self.program.repack:
            self._check_repack(t)
        if self.router_fuzz is not None:
            self.router_fuzz.check_terminal(t)
        snap = self.controller.metrics.snapshot()
        mismatches = int(snap["counters"].get(
            "columnar_plan_mismatches", 0))
        violations = [str(v) for v in self.monitor.violations]
        if self.verify_columnar and mismatches:
            violations.append(
                f"columnar: {mismatches} plan mismatch(es) vs the "
                "Python oracle under verify_columnar_plans")
        return ChaosResult(
            seed=program.seed,
            ok=not violations,
            violations=violations,
            passes=self.passes, converged_at=converged_at,
            description=program.describe(),
            wall_seconds=_time.perf_counter() - t0,
            reconcile_errors=self.reconcile_errors,
            repairs=int(snap["counters"].get("slice_repairs_started",
                                             0)),
            repacks=int(snap["counters"].get(
                "repack_migrations_started", 0)),
            columnar_mismatches=mismatches)


def run_scenario(program_or_seed, *, profile: str = "mixed",
                 drive: str = "pump", schedules: int = 3,
                 reconcile_shards: int = 0,
                 verify_columnar: bool = False) -> ChaosResult:
    """Execute one scenario program (or generate it from a seed).

    ``drive="sched"`` replays the same program under the deterministic
    scheduler with real informer watch threads, sweeping ``schedules``
    seeded interleavings; the LAST interleaving's result is returned
    with any earlier violation carried over.

    ``reconcile_shards`` attaches the ISSUE 13 sharded planner to the
    controller (0 = the serial oracle); the invariant catalog is
    asserted unchanged either way.
    """
    program = (generate(program_or_seed, profile=profile)
               if isinstance(program_or_seed, int) else program_or_seed)
    if drive == "pump":
        return _Run(program, reconcile_shards=reconcile_shards,
                    verify_columnar=verify_columnar).execute()
    if drive != "sched":
        raise ValueError(f"unknown drive mode {drive!r}")
    from tpu_autoscaler.testing.sched import run_schedule

    results: list[ChaosResult] = []

    def scenario(sched) -> None:
        # Threaded twin of _Run: the normal constructor (informer
        # forced on — interleaving coverage is the point), then live
        # watch threads instead of the pump drive.
        run = _Run(dataclasses.replace(program, informer=True),
                   reconcile_shards=reconcile_shards,
                   verify_columnar=verify_columnar)
        run.informer.start()
        # Threads pump the caches; _step still calls pump() — with live
        # watches that is a no-op-ish double drain, so drop it.
        run.informer.pump = lambda: None
        try:
            results.append(run.execute())
        finally:
            run.informer.stop()

    for i in range(schedules):
        run_schedule(scenario, seed=program.seed + i, max_steps=2_000_000)
    merged = results[-1]
    for r in results[:-1]:
        if not r.ok:
            merged = dataclasses.replace(
                merged, ok=False, violations=r.violations + merged.violations)
    return merged


def run_corpus(seeds, *, profile: str = "mixed",
               budget_seconds: float | None = None,
               progress=None,
               reconcile_shards: int = 0,
               verify_columnar: bool = False
               ) -> tuple[list[ChaosResult], bool]:
    """Run many seeds; returns (results, budget_blown).  Stops early —
    with the flag set — if the wall-clock budget runs out before the
    corpus completes, so CI fails loudly instead of hanging."""
    t0 = _time.perf_counter()
    results: list[ChaosResult] = []
    for seed in seeds:
        if budget_seconds is not None \
                and _time.perf_counter() - t0 > budget_seconds:
            return results, True
        result = run_scenario(seed, profile=profile,
                              reconcile_shards=reconcile_shards,
                              verify_columnar=verify_columnar)
        results.append(result)
        if progress is not None:
            progress(result)
    return results, False
