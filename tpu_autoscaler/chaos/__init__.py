"""Generative chaos engine (ISSUE 7).

Seeded random *scenario programs* — workload churn plus API brownouts,
watch storms, 410 floods, stockouts (up-front and mid-provision),
preemptions, and partial slice host failures — compiled into event
schedules and driven through the same fake-cluster loop as ``sim.py``,
with property invariants asserted at every step:

- no stranded chips;
- never a double provision (the supply guard honored);
- slices only ever deleted whole, never a lone-host backfill;
- convergence under every seed;
- every scale-up / slice-repair trace complete in the flight recorder.

``python -m tpu_autoscaler.chaos --seed-corpus`` runs the CI corpus
(docs/CHAOS.md: scenario grammar, invariant catalog, seed triage).
Failures found here get promoted to ``testing/chaosfixtures.py``.
"""

from tpu_autoscaler.chaos.engine import ChaosResult, run_corpus, run_scenario
from tpu_autoscaler.chaos.scenario import Event, ScenarioProgram, generate

__all__ = [
    "ChaosResult",
    "Event",
    "ScenarioProgram",
    "generate",
    "run_corpus",
    "run_scenario",
]
