"""Chaos CLI (ISSUE 7): ``python -m tpu_autoscaler.chaos``.

Exit codes (scripts/ci_gate.sh keys on them):

- 0 — every seed held every invariant;
- 2 — at least one invariant violation (seeds printed for triage:
      replay with ``--seed N -v``, then promote the failure to
      ``testing/chaosfixtures.py`` — docs/CHAOS.md workflow);
- 3 — the wall-clock budget ran out before the corpus finished.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_autoscaler.chaos.engine import run_corpus, run_scenario
from tpu_autoscaler.chaos.scenario import PROFILES, generate


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_autoscaler.chaos",
        description="Generative chaos corpus over the control loop "
                    "(docs/CHAOS.md).")
    parser.add_argument("--seed-corpus", action="store_true",
                        help="run the seeded corpus (--seeds of them, "
                             "from --seed0)")
    parser.add_argument("--seeds", type=int, default=200,
                        help="corpus size (default 200)")
    parser.add_argument("--seed0", type=int, default=0,
                        help="first seed (default 0)")
    parser.add_argument("--seed", type=int, default=None,
                        help="run ONE seed (triage mode)")
    parser.add_argument("--profile", default="mixed",
                        choices=PROFILES,
                        help="fault alphabet (docs/CHAOS.md; 'policy' "
                             "runs recurring traffic with the "
                             "PolicyEngine attached)")
    parser.add_argument("--drive", default="pump",
                        choices=("pump", "sched"),
                        help="threadless pump (fast) or the "
                             "DeterministicScheduler with real watch "
                             "threads (interleaving sweep)")
    parser.add_argument("--reconcile-shards", type=int, default=0,
                        dest="reconcile_shards",
                        help="attach the ISSUE 13 sharded planner "
                             "(shard_min_gangs=0 so every pass "
                             "exercises fan-out/merge; 0 = the "
                             "serial oracle)")
    parser.add_argument("--verify-columnar", action="store_true",
                        dest="verify_columnar",
                        help="run the Python planner as a property "
                             "oracle beside the ISSUE 17 columnar "
                             "fast path on every pass; any plan "
                             "mismatch fails the seed")
    parser.add_argument("--budget", type=float, default=600.0,
                        help="corpus wall-clock budget seconds "
                             "(default 600; exit 3 when blown)")
    parser.add_argument("--json", dest="json_path", default=None,
                        help="also write results as JSON to this file")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="per-seed result lines")
    args = parser.parse_args(argv)

    if args.seed is None and not args.seed_corpus:
        parser.error("pass --seed N (triage) or --seed-corpus (CI)")

    if args.seed is not None:
        program = generate(args.seed, profile=args.profile)
        print(program.describe())
        for event in program.events:
            print(f"  t={event.t:7.1f}  {event.kind}  {event.args}")
        result = run_scenario(program, drive=args.drive,
                              reconcile_shards=args.reconcile_shards,
                              verify_columnar=args.verify_columnar)
        print(result.describe())
        return 0 if result.ok else 2

    seeds = range(args.seed0, args.seed0 + args.seeds)

    def progress(result) -> None:
        if args.verbose or not result.ok:
            print(result.describe(), flush=True)

    results, budget_blown = run_corpus(
        seeds, profile=args.profile, budget_seconds=args.budget,
        progress=progress, reconcile_shards=args.reconcile_shards,
        verify_columnar=args.verify_columnar)
    failures = [r for r in results if not r.ok]
    converged = sum(1 for r in results if r.converged_at is not None)
    repairs = sum(r.repairs for r in results)
    repacks = sum(r.repacks for r in results)
    wall = sum(r.wall_seconds for r in results)
    rp = (f", {repacks} repack migrations exercised" if repacks
          else "")
    vc = ""
    if args.verify_columnar:
        mismatches = sum(r.columnar_mismatches for r in results)
        vc = f", {mismatches} columnar plan mismatches"
    print(f"chaos corpus: {len(results)}/{len(seeds)} seeds run, "
          f"{len(failures)} failing, {converged} converged, "
          f"{repairs} slice repairs exercised{rp}{vc}, {wall:.1f}s wall "
          f"(budget {args.budget:g}s)")
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump({"profile": args.profile,
                       "seeds": [args.seed0, args.seed0 + args.seeds],
                       "failures": [dataclass_dict(r) for r in failures],
                       "run": len(results),
                       "converged": converged,
                       "repairs": repairs,
                       "wall_seconds": wall}, f, indent=2)
    if budget_blown:
        print(f"BUDGET EXCEEDED after {len(results)} seeds — the corpus "
              f"did not finish inside {args.budget:g}s", file=sys.stderr)
        return 3
    if failures:
        print("failing seeds (replay: python -m tpu_autoscaler.chaos "
              f"--seed N --profile {args.profile}): "
              + ", ".join(str(r.seed) for r in failures),
              file=sys.stderr)
        return 2
    return 0


def dataclass_dict(result) -> dict:
    import dataclasses

    return dataclasses.asdict(result)


if __name__ == "__main__":
    sys.exit(main())
