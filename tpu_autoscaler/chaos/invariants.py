"""Property invariants asserted over every chaos step (docs/CHAOS.md).

The monitor watches the fake cluster from OUTSIDE the controller — it
reads the apiserver's verb log and node/pod store, plus the planner's
in-flight view — so a controller bug cannot hide itself by also
corrupting the evidence.  Engine-injected faults (host kills, node GC)
are registered with the monitor so they are never mistaken for
controller actions.

Step invariants:

- **running-pod safety / slice-atomic deletes** — a node the CONTROLLER
  deletes never still hosts a live Running pod, and the controller's
  TPU deletions always take the whole slice in one pass;
- **no lone-host backfill** — once a slice id loses a host, no node is
  ever added back under that id (replacement is a fresh slice);
- **no double provision** — at most one planner-visible in-flight entry
  (actuator in-flight + supply-guard holds) per gang key.

Terminal invariants (after the quiet tail): convergence, no stranded
chips, flight-recorder trace completeness (``obs.trace_gaps``).
"""

from __future__ import annotations

import dataclasses

SLICE_LABEL = "autoscaler.tpu.dev/slice-id"


@dataclasses.dataclass
class Violation:
    seed: int
    t: float
    invariant: str
    detail: str

    def __str__(self) -> str:
        return (f"[seed {self.seed} t={self.t:g}] {self.invariant}: "
                f"{self.detail}")


class InvariantMonitor:
    """Step-wise property checks over one scenario run."""

    def __init__(self, seed: int, kube, controller) -> None:
        self.seed = seed
        self._kube = kube
        self._controller = controller
        self.violations: list[Violation] = []
        #: Node names the ENGINE deleted/killed (never the controller).
        self.injected_deletes: set[str] = set()
        self._verb_cursor = 0
        # slice id -> (last host count seen, has shrunk below it)
        self._slice_watermark: dict[str, tuple[int, bool]] = {}
        # slice id -> sim time it was first observed workload-free
        # (reset when busy): the stranded-chips clock.
        self._idle_since: dict[str, float] = {}
        self._pre_nodes: dict[str, dict] = {}
        self._pre_running: dict[str, set[str]] = {}

    def _fail(self, t: float, invariant: str, detail: str) -> None:
        self.violations.append(Violation(self.seed, t, invariant, detail))

    # -- per-step ---------------------------------------------------------

    def before_pass(self) -> None:
        self._pre_nodes = {n["metadata"]["name"]: n
                           for n in self._kube.list_nodes()}
        running: dict[str, set[str]] = {}
        for p in self._kube.list_pods():
            node = p["spec"].get("nodeName")
            if node and p["status"].get("phase") == "Running":
                running.setdefault(node, set()).add(
                    p["metadata"]["name"])
        self._pre_running = running

    def after_pass(self, t: float) -> None:
        new_verbs = self._kube.verb_log[self._verb_cursor:]
        self._verb_cursor = len(self._kube.verb_log)
        deleted = [v[1] for v in new_verbs if v[0] == "delete_node"
                   and v[1] not in self.injected_deletes]
        live_pods = {p["metadata"]["name"]
                     for p in self._kube.list_pods()}
        remaining = {n["metadata"]["name"]: n
                     for n in self._kube.list_nodes()}

        touched_slices: set[str] = set()
        for name in deleted:
            pre = self._pre_nodes.get(name)
            if pre is None:
                continue
            still_running = self._pre_running.get(name, set()) & live_pods
            if still_running:
                self._fail(t, "running-pod-safety",
                           f"controller deleted node {name} while pods "
                           f"{sorted(still_running)} still exist Running")
            sid = pre["metadata"].get("labels", {}).get(SLICE_LABEL)
            if sid and pre["metadata"].get("labels", {}).get(
                    "cloud.google.com/gke-tpu-accelerator"):
                touched_slices.add(sid)
        for sid in touched_slices:
            survivors = [
                name for name, n in remaining.items()
                if n["metadata"].get("labels", {}).get(SLICE_LABEL) == sid
                and name not in self.injected_deletes]
            if survivors:
                self._fail(t, "whole-slice-deletes",
                           f"slice {sid} partially deleted; hosts "
                           f"{sorted(survivors)} left behind")

        # Lone-host backfill: a shrunk slice id never grows again.
        counts: dict[str, int] = {}
        for name, n in remaining.items():
            sid = n["metadata"].get("labels", {}).get(SLICE_LABEL)
            if sid and n["metadata"].get("labels", {}).get(
                    "cloud.google.com/gke-tpu-accelerator"):
                counts[sid] = counts.get(sid, 0) + 1
        for sid, count in counts.items():
            last, shrunk = self._slice_watermark.get(sid, (count, False))
            if count < last:
                shrunk = True
            elif shrunk and count > last:
                self._fail(t, "no-lone-host-backfill",
                           f"slice {sid} regrew to {count} hosts after "
                           f"losing one (ICI domains are replaced whole, "
                           f"never backfilled)")
            self._slice_watermark[sid] = (count, shrunk)
        for sid in [s for s in self._slice_watermark if s not in counts]:
            del self._slice_watermark[sid]  # slice fully gone; ids are fresh

        # Idle clock per slice (feeds the terminal stranded-chips check).
        busy: set[str] = set()
        for p in self._kube.list_pods():
            node = p["spec"].get("nodeName")
            if node and p["status"].get("phase") == "Running":
                n = remaining.get(node)
                if n is not None:
                    sid = n["metadata"].get("labels", {}).get(SLICE_LABEL)
                    if sid:
                        busy.add(sid)
        for sid in counts:
            if sid in busy:
                self._idle_since.pop(sid, None)
            else:
                self._idle_since.setdefault(sid, t)
        for sid in [s for s in self._idle_since if s not in counts]:
            del self._idle_since[sid]

        # Double provision: one planner-visible in-flight entry per key.
        per_key: dict[tuple, int] = {}
        for inf in self._controller._in_flight():
            if inf.gang_key is not None:
                per_key[inf.gang_key] = per_key.get(inf.gang_key, 0) + 1
        for key, n in per_key.items():
            if n > 1:
                self._fail(t, "no-double-provision",
                           f"{n} concurrent in-flight provisions for "
                           f"gang {key} (supply guard breached)")

        # Cost conservation (ISSUE 11, docs/COST.md): every pass the
        # ledger closed, the per-state chip attribution must sum
        # EXACTLY (int equality, zero tolerance) to the fleet chips
        # the reconciler independently counted — every chip-second is
        # accounted, none twice.  Crash-only passes (brownouts) skip
        # the close; the ledger's pair is only compared when fresh.
        ledger = getattr(self._controller, "cost", None)
        if ledger is not None and ledger.last_conservation is not None:
            attributed, fleet = ledger.last_conservation
            if attributed != fleet:
                self._fail(t, "cost-conservation",
                           f"ledger attributed {attributed} chips vs "
                           f"{fleet} fleet chips (a chip-second went "
                           f"unaccounted or was counted twice)")

        # Profiler self-time conservation (ISSUE 20,
        # docs/OBSERVABILITY.md "Control-plane profiling"): every pass
        # the profiler closed, the per-phase self times plus the
        # ``other`` residual must sum to the pass window within the
        # declared tolerance — the profiler's own violation counter is
        # checked per step so the FIRST broken pass names itself, and
        # its per-pass ring must hold its bound.
        prof = getattr(self._controller, "profiler", None)
        if prof is not None and prof.conservation_violations:
            self._fail(t, "profiler-conservation",
                       f"{prof.conservation_violations} self-time "
                       f"conservation violation(s) (phase self times "
                       f"no longer sum to the pass window)")
        if prof is not None and len(prof.ring()) > prof.ring_limit:
            self._fail(t, "profiler-ring-bounded",
                       f"profiler ring holds {len(prof.ring())} passes "
                       f"(bound {prof.ring_limit})")

    # -- terminal ---------------------------------------------------------

    def check_converged(self, t: float, live_jobs: dict[str, list[str]]
                        ) -> bool:
        """True when every live job runs, nothing is pending or in
        flight, and no repair is open — the convergence predicate."""
        pods = {p["metadata"]["name"]: p for p in self._kube.list_pods()}
        for names in live_jobs.values():
            for name in names:
                pod = pods.get(name)
                if pod is None or pod["status"].get("phase") != "Running":
                    return False
        if any(p["status"].get("phase") == "Pending"
               for p in pods.values()):
            return False
        if self._controller._in_flight():
            return False
        if self._controller._slice_repairs:
            return False
        return True

    def check_terminal(self, t: float, live_jobs: dict[str, list[str]],
                       *, converged: bool,
                       reclaim_window: float) -> None:
        if not converged:
            pending = [p["metadata"]["name"]
                       for p in self._kube.list_pods()
                       if p["status"].get("phase") == "Pending"]
            self._fail(t, "convergence",
                       f"scenario did not converge: pending={pending} "
                       f"in_flight={len(self._controller._in_flight())} "
                       f"repairs={list(self._controller._slice_repairs)}")
            return
        # Stranded chips: every TPU slice either hosts Running workload,
        # has been idle for less than the reclaim window, or is being
        # drained (cordoned).  The idle clock comes from after_pass.
        cordoned: set[str] = set()
        for n in self._kube.list_nodes():
            if n.get("spec", {}).get("unschedulable"):
                sid = n["metadata"].get("labels", {}).get(SLICE_LABEL)
                if sid:
                    cordoned.add(sid)
        for sid, since in sorted(self._idle_since.items()):
            if sid in cordoned:
                continue
            idle_for = t - since
            if idle_for > reclaim_window:
                self._fail(t, "no-stranded-chips",
                           f"slice {sid} idle for {idle_for:g}s — "
                           f"capacity leaked past the reclaim window "
                           f"({reclaim_window:g}s)")

        # Gang ICI integrity: a live TPU gang's pods all share ONE
        # slice — a gang silently split across ICI domains (the
        # lone-host-backfill failure mode seen end-to-end) "runs" by
        # pod phase while the job's collective is broken.
        nodes_by_name = {n["metadata"]["name"]: n
                         for n in self._kube.list_nodes()}
        pods_by_name = {p["metadata"]["name"]: p
                        for p in self._kube.list_pods()}
        for job, names in sorted(live_jobs.items()):
            slices: set[str] = set()
            for name in names:
                pod = pods_by_name.get(name)
                node = nodes_by_name.get(
                    (pod or {}).get("spec", {}).get("nodeName", ""))
                if node is None:
                    continue
                sid = node["metadata"].get("labels", {}).get(SLICE_LABEL)
                if sid and node["metadata"].get("labels", {}).get(
                        "cloud.google.com/gke-tpu-accelerator"):
                    slices.add(sid)
            if len(slices) > 1:
                self._fail(t, "gang-ici-integrity",
                           f"job {job} runs split across slices "
                           f"{sorted(slices)} — one gang, one ICI "
                           f"domain")

        # Cost conservation, terminal half: zero violations across the
        # WHOLE run — including passes whose per-step check raced a
        # brownout (the ledger counts its own misses).
        ledger = getattr(self._controller, "cost", None)
        if ledger is not None and ledger.conservation_violations:
            self._fail(t, "cost-conservation",
                       f"{ledger.conservation_violations} conservation "
                       f"violation(s) over the run (attributed != "
                       f"fleet chip-seconds)")

        # Profiler conservation, terminal half: zero self-time
        # violations across the WHOLE run.  Brownout-crashed passes
        # are fine — an abandoned pass is a forced close (its own
        # counter), never a conservation violation, so this stays
        # exactly zero even on fault-heavy seeds.
        prof = getattr(self._controller, "profiler", None)
        if prof is not None and prof.conservation_violations:
            self._fail(t, "profiler-conservation",
                       f"{prof.conservation_violations} self-time "
                       f"conservation violation(s) over the run (phase "
                       f"self times + other != pass window)")

        # Flight-recorder completeness: every finished trace is whole.
        from tpu_autoscaler.obs import trace_gaps

        dump = self._controller.recorder.dump(
            tracer=self._controller.tracer)
        finished: set[str] = set()
        for span in dump["spans"]:
            if span["name"] in ("scale_up", "slice_repair", "repack") \
                    and span["parent_id"] is None \
                    and span["end"] is not None:
                finished.add(span["trace_id"])
        for trace_id in sorted(finished):
            for gap in trace_gaps(dump, trace_id):
                self._fail(t, "trace-completeness", gap)
        for span in dump.get("active_spans", []):
            if span["name"] in ("scale_up", "slice_repair", "repack"):
                self._fail(t, "trace-completeness",
                           f"trace {span['trace_id']}: {span['name']} "
                           f"span still open after convergence")
