"""Scenario grammar: seed -> scenario program (docs/CHAOS.md).

A program is workloads plus a time-ordered fault schedule.  Generation
is a pure function of the seed (``random.Random(seed)``), so every
corpus failure replays exactly from its seed number.  Fault windows all
close before the quiet tail, making convergence a decidable property.

Event kinds (args in parentheses):

- ``brownout`` (duration)     — apiserver fails every verb in a window;
- ``watch_storm`` (count)     — burst of irrelevant object churn;
- ``flood_410``               — etcd compaction: watch cursors go stale,
                                every watcher must relist;
- ``stockout`` (duration)     — provisions in flight FAIL (zone dry);
- ``mid_provision_stockout``  — provisions already in flight FAIL once;
- ``preempt``                 — impending-termination taint on a random
                                busy unit (spot reclaim notice);
- ``host_fail`` (mode)        — one host of a live multi-host slice
                                goes NotReady or is deleted (the
                                partial-slice failure slice repair
                                exists for);
- ``slow_decode`` (duration, factor) — serving profile only: one
                                fuzz replica's decode durations
                                multiply for the window, pushing its
                                completions past the SLO bound — the
                                request-trace sampler's tail capture
                                must fire, gap-free and bounded
                                (ISSUE 14).

Workloads (ISSUE 8 additions):

- ``jobset_slices > 1`` — a multislice JobSet: one gang per slice,
  provisioned as ONE atomic multislice unit (gang-ICI-integrity is
  asserted per member job — each job on ONE slice);
- ``repeat``/``repeat_gap`` — a recurring job: after completing, the
  engine re-launches it (run-suffixed name, same base) — the traffic
  the ``policy`` profile's PolicyEngine learns from, and the surface
  where mispredictions must never violate no-double-provision or
  no-stranded-chips.
"""

from __future__ import annotations

import dataclasses
import random

#: Multi-host shapes small enough to keep a corpus seed sub-second;
#: v5p-16 covers the acceptance scenario's generation (4-host v5p).
GANG_SHAPES = ("v5e-8", "v5e-16", "v5e-32", "v5p-16")

#: Sim-seconds of guaranteed fault-free tail before convergence is
#: judged (every generated event fires before ``until - QUIET_TAIL``).
QUIET_TAIL = 300.0

#: Chaos-scale burn-rule timing for the ``alerts`` profile.  The
#: engine builds its AlertRule FROM these (engine._alert_engine), and
#: ``generate`` derives the post-regression resolution slack from the
#: same numbers — widening the rule's windows can never silently
#: outrun the driven phase and fail the resolves-at-terminal
#: invariant.
ALERTS_FAST_WINDOW = 120.0
ALERTS_SLOW_WINDOW = 600.0
ALERTS_FOR_PASSES = 2
ALERTS_CLEAR_PASSES = 3

#: Known profiles (docs/CHAOS.md; ``policy`` is ISSUE 8, ``serving``
#: is ISSUE 9 — fuzz the serving metrics-adapter path under the mixed
#: fault alphabet; ``alerts`` is ISSUE 10 — the SLO burn-rate alert
#: gate: injected scale-up-latency regressions must fire the alert
#: and resolve, quiet seeds must stay silent; ``repack`` is ISSUE 12
#: — long-running gangs on on-demand supply with pre-seeded idle SPOT
#: slices, the repacker ON, and migrations raced by spot reclamation,
#: destination stockouts and mid-drain gang deletion; ``router`` is
#: ISSUE 18 — the serving alphabet plus a routed request stream
#: through RouterCore: replica death mid-request, affinity staleness
#: via restart epoch bumps, hedge storms and counter resets during
#: hedges, with no-lost-requests + no-double-completion at terminal).
PROFILES = ("mixed", "faults", "api", "repair", "policy", "serving",
            "alerts", "repack", "router")


@dataclasses.dataclass(frozen=True)
class Event:
    t: float
    kind: str
    args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Workload:
    job: str
    shape: str
    arrival: float
    # Per-step completion probability once fully Running (0 = runs to
    # scenario end; the terminal convergence check then requires it
    # Running).
    completion_prob: float = 0.0
    # False = accelerator-only selectors (no topology pin): the fitter
    # sizes from observed chip demand — the surface partial-gang
    # planning bugs live on.
    pinned: bool = True
    # Multislice JobSet: one gang per slice (one atomic provisioning
    # unit of N slices); 1 = a plain single-slice Job.
    jobset_slices: int = 1
    # Recurring job: re-launched this many times after completing,
    # ``repeat_gap`` sim-seconds after each completion (run-suffixed
    # names share one base, so the recurring predictor can mine them).
    repeat: int = 0
    repeat_gap: float = 60.0


@dataclasses.dataclass(frozen=True)
class ScenarioProgram:
    seed: int
    step: float
    until: float                  # end of the driven (event) phase
    settle: float                 # extra sim-seconds allowed to converge
    workloads: tuple[Workload, ...]
    events: tuple[Event, ...]
    informer: bool                # cached observe path vs serial LISTs
    provision_delay: float
    stagger_seconds: float
    max_total_chips: int
    # ISSUE 8: run the scenario with the PolicyEngine attached — its
    # prewarms/holds ride the same corpus invariants.
    policy: bool = False
    # ISSUE 9: run with the serving metrics adapter + ServingScaler
    # attached, fed by fuzzed replica snapshots (restarts mid-window,
    # counter resets, stale/out-of-order deliveries).  The adapter's
    # step invariant: counter resets must NEVER yield negative rates,
    # and the incremental pool sums must match a from-scratch rebuild.
    serving: bool = False
    # ISSUE 10: run with the chaos-scale burn-rate AlertEngine
    # attached.  ~Half the seeds carry a ``latency_regression`` event
    # (provisions stall until the window closes → scale-up latencies
    # blow the SLO bound); the terminal invariant asserts the alert
    # FIRED and RESOLVED on those seeds and NEVER fired on the quiet
    # ones (the zero-false-positive half of the gate).
    alerts: bool = False
    # ISSUE 11: provision spot/preemptible capacity (~25% of seeds,
    # drawn from a DERIVED rng stream so every pre-existing seed
    # program stays byte-identical).  Exercises the cost ledger's
    # price-tier dimension — spot-labeled nodes must conserve exactly
    # like on-demand ones — across the whole fault alphabet.
    preemptible: bool = False
    # ISSUE 12: run with the repacker ON (chaos-scale RepackConfig)
    # over ``repack_spot_shapes`` idle spot slices pre-seeded at t=0;
    # the terminal invariants add never-net-negative-savings and the
    # guard-capped abort-cost bound on top of the standard catalog.
    repack: bool = False
    repack_spot_shapes: tuple[str, ...] = ()
    # ISSUE 18: drive a routed request stream (RouterCore over the
    # serving adapter) alongside the serving fuzz — dispatch, affinity,
    # hedging and drain migration raced by the full fault alphabet;
    # terminal invariants add no-lost-requests + no-double-completion.
    router: bool = False

    def describe(self) -> str:
        kinds: dict[str, int] = {}
        for e in self.events:
            kinds[e.kind] = kinds.get(e.kind, 0) + 1
        faults = ",".join(f"{k}x{n}" for k, n in sorted(kinds.items())) \
            or "none"
        tags = []
        if any(w.jobset_slices > 1 for w in self.workloads):
            tags.append("multislice")
        if self.policy:
            tags.append("policy")
        if self.router:
            tags.append("router")
        elif self.serving:
            tags.append("serving")
        if self.alerts:
            tags.append("alerts")
            tags.append("regression" if any(
                e.kind == "latency_regression" for e in self.events)
                else "quiet")
        if self.preemptible:
            tags.append("spot")
        if self.repack:
            tags.append(
                f"repack:{'/'.join(self.repack_spot_shapes) or 'dry'}")
        tagtxt = f" [{'+'.join(tags)}]" if tags else ""
        return (f"seed={self.seed} jobs={len(self.workloads)} "
                f"({'/'.join(w.shape for w in self.workloads)}){tagtxt} "
                f"faults=[{faults}] informer={self.informer} "
                f"delay={self.provision_delay:g}s "
                f"clamp={self.max_total_chips}")


def generate(seed: int, *, profile: str = "mixed",
             multislice: bool = True) -> ScenarioProgram:
    """Compile one seeded scenario program.

    Profiles narrow the fault alphabet for triage (docs/CHAOS.md):
    ``mixed`` (default, everything), ``faults`` (no API-layer chaos),
    ``api`` (only API-layer chaos), ``repair`` (always a host
    failure), ``policy`` (PolicyEngine enabled over recurring traffic
    with the mixed fault alphabet — mispredictions under fire).

    ``multislice=False`` suppresses the jobset overlay: promoted
    regression fixtures pin pre-ISSUE-8 seed programs exactly.

    ``serving`` (ISSUE 9): the mixed API/fault alphabet plus a fuzzed
    serving-replica fleet feeding the metrics adapter — replica
    restarts mid-window, raw counter resets, stale and out-of-order
    snapshot deliveries (scheduled as ``replica_restart`` /
    ``counter_reset`` / ``stale_burst`` / ``replica_churn`` events the
    engine's serving driver consumes), with the ServingScaler's
    advisory demand riding the normal corpus invariants.
    """
    if profile not in PROFILES:
        raise ValueError(f"unknown chaos profile {profile!r}")
    rng = random.Random(seed)
    # The multislice draw rides a DERIVED stream so pre-ISSUE-8 seeds
    # keep their exact programs (promoted regression fixtures in
    # testing/chaosfixtures.py pin seed numbers).
    rng_ms = random.Random(seed ^ 0x515CE5)
    informer = rng.random() < 0.7
    jobs = rng.randint(1, 3)
    workloads = []
    for i in range(jobs):
        shape = rng.choice(GANG_SHAPES)
        if profile in ("repair", "repack") and i == 0:
            # Guarantee a multi-host victim for the host failure /
            # a multi-host migration source for the repacker.
            shape = rng.choice(("v5e-16", "v5e-32", "v5p-16"))
        # Draw order matters: arrival -> completion -> pinned is the
        # pre-ISSUE-8 stream (keyword evaluation order of the original
        # constructor call), and seed programs must stay reproducible.
        arrival = rng.uniform(0.0, 120.0)
        completion = rng.choice((0.0, 0.0, 0.01))
        pinned = rng.random() < 0.6
        repeat, repeat_gap = 0, 60.0
        if profile == "policy" and i == 0:
            # The recurring job the PolicyEngine learns from: completes
            # quickly and re-arrives on a quasi-stable gap.  (New
            # profile: its extra draws shift no legacy stream.)
            repeat = rng.randint(2, 4)
            repeat_gap = rng.uniform(50.0, 90.0)
            completion = 0.25
        workloads.append(Workload(
            job=f"chaos-{seed}-{i}", shape=shape,
            arrival=arrival, completion_prob=completion, pinned=pinned,
            repeat=repeat, repeat_gap=repeat_gap))
    if multislice and profile in ("mixed", "faults") \
            and workloads[-1].repeat == 0 and rng_ms.random() < 0.25:
        # Multislice jobset (ISSUE 8 headroom item): the last workload
        # becomes one gang per slice over DCN — small shapes so the
        # chip clamp still admits the whole atomic unit.
        workloads[-1] = dataclasses.replace(
            workloads[-1],
            shape=rng_ms.choice(("v5e-8", "v5e-16")),
            jobset_slices=2)

    # ``alerts`` keeps the quiet API alphabet only: supply-side faults
    # (stockouts, host failures) produce legitimately slow scale-ups —
    # true positives — and the quiet half of the alert gate needs
    # latency guaranteed under the SLO bound.
    api_chaos = profile in ("mixed", "api", "policy", "serving",
                            "alerts", "router")
    fault_chaos = profile in ("mixed", "faults", "repair", "policy",
                              "serving", "repack", "router")
    events: list[Event] = []

    def fire(probability: float) -> bool:
        return rng.random() < probability

    if api_chaos and fire(0.5):
        start = rng.uniform(60.0, 260.0)
        events.append(Event(start, "brownout",
                            {"duration": rng.uniform(15.0, 60.0)}))
    if api_chaos and informer and fire(0.4):
        events.append(Event(rng.uniform(30.0, 300.0), "watch_storm",
                            {"count": rng.randint(20, 60)}))
    if api_chaos and informer and fire(0.4):
        for _ in range(rng.randint(1, 3)):
            events.append(Event(rng.uniform(30.0, 300.0), "flood_410"))
    if fault_chaos and fire(0.5):
        start = rng.uniform(0.0, 200.0)
        events.append(Event(start, "stockout",
                            {"duration": rng.uniform(30.0, 120.0)}))
    if fault_chaos and fire(0.35):
        events.append(Event(rng.uniform(20.0, 260.0),
                            "mid_provision_stockout"))
    if fault_chaos and fire(0.3):
        events.append(Event(rng.uniform(150.0, 330.0), "preempt"))
    if profile == "repair" or (fault_chaos and fire(0.5)):
        events.append(Event(
            rng.uniform(150.0, 330.0), "host_fail",
            {"mode": rng.choice(("notready", "delete"))}))
    if profile in ("serving", "router"):
        # Serving-path faults, consumed by the engine's serving
        # driver (new profiles: their draws shift no legacy stream).
        # The router profile rides the same alphabet — replica
        # restarts are its affinity-staleness injector (fresh epoch
        # under the table's feet) and counter resets corrupt the
        # drain-credit rates mid-hedge.
        for _ in range(rng.randint(1, 3)):
            events.append(Event(rng.uniform(30.0, 300.0),
                                "replica_restart"))
        if fire(0.7):
            events.append(Event(rng.uniform(30.0, 300.0),
                                "counter_reset"))
        if fire(0.7):
            events.append(Event(rng.uniform(30.0, 300.0), "stale_burst",
                                {"count": rng.randint(3, 12)}))
        if fire(0.5):
            events.append(Event(rng.uniform(60.0, 300.0),
                                "replica_churn",
                                {"add": rng.randint(0, 2),
                                 "remove": rng.randint(0, 2)}))
        # ISSUE 14 (derived stream: legacy serving seed programs keep
        # their exact draws): a per-replica decode-tick inflation
        # window — one replica's decode durations multiply by
        # ``factor`` for ``duration`` sim-seconds, pushing its
        # completions past the SLO bound so the request-trace
        # sampler's tail capture fires under the full fault alphabet.
        rng_sd = random.Random(seed ^ 0x51DEC)
        if rng_sd.random() < 0.6:
            events.append(Event(
                rng_sd.uniform(60.0, 280.0), "slow_decode",
                {"duration": rng_sd.uniform(30.0, 90.0),
                 "factor": rng_sd.uniform(3.0, 8.0)}))

    if profile == "router":
        # ISSUE 18 (new profile: derived rng stream).  Every seed gets
        # at least one hedge storm — a window where victim replicas
        # wedge (stop completing, stop admitting) so a burst of
        # outstanding requests becomes hedge-eligible at once; the
        # router must re-dispatch each EXACTLY once.  ~Half the seeds
        # land a counter reset INSIDE the storm (the drain-credit
        # rates corrupt mid-hedge), and ~half remove a replica with
        # requests in flight (death mid-request -> the DrainReceipt
        # migration path must re-home the remainder losslessly).
        rng_rt = random.Random(seed ^ 0x207E12)
        start = rng_rt.uniform(60.0, 240.0)
        duration = rng_rt.uniform(25.0, 60.0)
        events.append(Event(start, "hedge_storm",
                            {"duration": duration}))
        if rng_rt.random() < 0.5:
            events.append(Event(
                start + rng_rt.uniform(0.0, duration), "counter_reset"))
        if rng_rt.random() < 0.4:
            events.append(Event(rng_rt.uniform(60.0, 280.0),
                                "hedge_storm",
                                {"duration": rng_rt.uniform(15.0, 40.0)}))
        if rng_rt.random() < 0.6:
            events.append(Event(rng_rt.uniform(80.0, 260.0),
                                "replica_churn",
                                {"add": rng_rt.randint(0, 1),
                                 "remove": 1}))

    repack_spot_shapes: tuple[str, ...] = ()
    if profile == "repack":
        # ISSUE 12 (new profile: derived rng stream, shifts no legacy
        # seed program).  Workloads run to scenario end — stable
        # migration sources.  Idle SPOT slices of the workloads' own
        # shapes ARRIVE (``spot_arrive``) after every gang has landed
        # on on-demand supply — the spot market freeing up is what
        # creates the displacement the repacker exists to fix;
        # seeding them at t=0 would just hand the gangs spot supply
        # directly and nothing would ever be wrongly placed.  The
        # profile-specific faults race the migration window:
        # ``spot_dry`` (the destination pool dries up — workload-free
        # spot slices vanish; the budget guard must abort) and
        # ``gang_delete`` (the job is deleted mid-drain; the
        # migration must close abandoned, bookkeeping-free).
        rng_rp = random.Random(seed ^ 0x12EAC)
        workloads = [dataclasses.replace(w, completion_prob=0.0,
                                         repeat=0)
                     for w in workloads]
        shapes = []
        for i, w in enumerate(workloads):
            if w.shape not in shapes \
                    and (i == 0 or rng_rp.random() < 0.7):
                shapes.append(w.shape)
        repack_spot_shapes = tuple(shapes)
        arrive = rng_rp.uniform(150.0, 210.0)
        for j, shape in enumerate(repack_spot_shapes):
            events.append(Event(arrive + 10.0 * j, "spot_arrive",
                                {"shape": shape}))
        if rng_rp.random() < 0.45:
            # Early enough to catch a migration pre-landing (abort),
            # late enough that some seeds see it land first (no-op).
            events.append(Event(arrive + rng_rp.uniform(5.0, 60.0),
                                "spot_dry"))
        if rng_rp.random() < 0.3:
            events.append(Event(arrive + rng_rp.uniform(5.0, 45.0),
                                "gang_delete"))

    regression_end = 0.0
    if profile == "alerts":
        # ISSUE 10 (new profile: derived rng stream, shifts no legacy
        # seed program).  ~Half the seeds inject a scale-up-latency
        # regression: provisions submitted inside the window stall
        # until it closes (engine applies the delay via
        # set_provision_delay), so the window length IS the injected
        # latency — sized to blow the 360 s SLO bound.  A dedicated
        # workload arrives just inside the window so every regression
        # seed is guaranteed a provision riding it.
        rng_al = random.Random(seed ^ 0xA1E27)
        if rng_al.random() < 0.5:
            start = rng_al.uniform(60.0, 140.0)
            # Injected latency ≈ duration − (arrival offset ≤ 25 s)
            # − (a brownout may hide the pending gang ≤ 60 s) − pass
            # granularity; the floor keeps the worst case STRICTLY
            # above the 360 s bound (a miss exactly ON the `le`
            # bucket bound counts as good — chaos-found, seed 119).
            duration = rng_al.uniform(475.0, 545.0)
            events.append(Event(start, "latency_regression",
                                {"duration": duration}))
            # The lag gang's shape must differ from every other
            # workload's: a same-shape slice freed by a completing
            # job would serve it WITHOUT a provision (bind-only
            # scale-up, no injected miss — chaos-found at seed 79).
            used = {w.shape for w in workloads}
            fresh = [s for s in ("v5e-8", "v5e-16", "v5e-32", "v5p-16")
                     if s not in used]
            workloads.append(Workload(
                job=f"chaos-{seed}-lag",
                shape=rng_al.choice(fresh),
                arrival=start + rng_al.uniform(5.0, 25.0),
                pinned=True))
            regression_end = start + duration

    events.sort(key=lambda e: e.t)
    last = max([e.t + e.args.get("duration", 0.0) for e in events],
               default=0.0)
    # Recurring traffic needs a longer driven phase: every repeat must
    # have ROOM to complete and re-arrive before the quiet tail.
    repeats_span = max(
        [w.arrival + (w.repeat + 1) * (w.repeat_gap + 120.0)
         for w in workloads if w.repeat > 0], default=0.0)
    until = max(last, repeats_span, 120.0) + QUIET_TAIL
    if regression_end:
        # The burn alert must RESOLVE before the terminal check: keep
        # the driven phase open until the miss ages out of the slow
        # burn window plus clear-hysteresis passes (5 s step) and a
        # few passes of slack — all derived from the rule constants.
        resolve_slack = (ALERTS_SLOW_WINDOW
                         + (ALERTS_CLEAR_PASSES + 7) * 5.0)
        until = max(until, regression_end + resolve_slack + QUIET_TAIL)
    # Spot tier (ISSUE 11): derived stream — legacy seed programs and
    # promoted fixtures keep their exact draws.
    rng_cost = random.Random(seed ^ 0x5C057)
    return ScenarioProgram(
        seed=seed, step=5.0, until=until, settle=600.0,
        workloads=tuple(workloads), events=tuple(events),
        informer=informer,
        provision_delay=rng.choice((10.0, 30.0, 60.0)),
        stagger_seconds=rng.choice((0.0, 0.0, 5.0)),
        max_total_chips=rng.choice((256, 1024)),
        policy=(profile == "policy"),
        serving=(profile in ("serving", "router")),
        alerts=(profile == "alerts"),
        router=(profile == "router"),
        # The repack profile needs its PROVISIONED supply on-demand —
        # a spot-provisioned gang has nothing cheaper to migrate to
        # (the pre-seeded idle slices are the spot side).
        preemptible=(rng_cost.random() < 0.25
                     and profile != "repack"),
        repack=(profile == "repack"),
        repack_spot_shapes=repack_spot_shapes)
