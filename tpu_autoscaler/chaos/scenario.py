"""Scenario grammar: seed -> scenario program (docs/CHAOS.md).

A program is workloads plus a time-ordered fault schedule.  Generation
is a pure function of the seed (``random.Random(seed)``), so every
corpus failure replays exactly from its seed number.  Fault windows all
close before the quiet tail, making convergence a decidable property.

Event kinds (args in parentheses):

- ``brownout`` (duration)     — apiserver fails every verb in a window;
- ``watch_storm`` (count)     — burst of irrelevant object churn;
- ``flood_410``               — etcd compaction: watch cursors go stale,
                                every watcher must relist;
- ``stockout`` (duration)     — provisions in flight FAIL (zone dry);
- ``mid_provision_stockout``  — provisions already in flight FAIL once;
- ``preempt``                 — impending-termination taint on a random
                                busy unit (spot reclaim notice);
- ``host_fail`` (mode)        — one host of a live multi-host slice
                                goes NotReady or is deleted (the
                                partial-slice failure slice repair
                                exists for).
"""

from __future__ import annotations

import dataclasses
import random

#: Multi-host shapes small enough to keep a corpus seed sub-second;
#: v5p-16 covers the acceptance scenario's generation (4-host v5p).
GANG_SHAPES = ("v5e-8", "v5e-16", "v5e-32", "v5p-16")

#: Sim-seconds of guaranteed fault-free tail before convergence is
#: judged (every generated event fires before ``until - QUIET_TAIL``).
QUIET_TAIL = 300.0


@dataclasses.dataclass(frozen=True)
class Event:
    t: float
    kind: str
    args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Workload:
    job: str
    shape: str
    arrival: float
    # Per-step completion probability once fully Running (0 = runs to
    # scenario end; the terminal convergence check then requires it
    # Running).
    completion_prob: float = 0.0
    # False = accelerator-only selectors (no topology pin): the fitter
    # sizes from observed chip demand — the surface partial-gang
    # planning bugs live on.
    pinned: bool = True


@dataclasses.dataclass(frozen=True)
class ScenarioProgram:
    seed: int
    step: float
    until: float                  # end of the driven (event) phase
    settle: float                 # extra sim-seconds allowed to converge
    workloads: tuple[Workload, ...]
    events: tuple[Event, ...]
    informer: bool                # cached observe path vs serial LISTs
    provision_delay: float
    stagger_seconds: float
    max_total_chips: int

    def describe(self) -> str:
        kinds: dict[str, int] = {}
        for e in self.events:
            kinds[e.kind] = kinds.get(e.kind, 0) + 1
        faults = ",".join(f"{k}x{n}" for k, n in sorted(kinds.items())) \
            or "none"
        return (f"seed={self.seed} jobs={len(self.workloads)} "
                f"({'/'.join(w.shape for w in self.workloads)}) "
                f"faults=[{faults}] informer={self.informer} "
                f"delay={self.provision_delay:g}s "
                f"clamp={self.max_total_chips}")


def generate(seed: int, *, profile: str = "mixed") -> ScenarioProgram:
    """Compile one seeded scenario program.

    Profiles narrow the fault alphabet for triage (docs/CHAOS.md):
    ``mixed`` (default, everything), ``faults`` (no API-layer chaos),
    ``api`` (only API-layer chaos), ``repair`` (always a host failure).
    """
    if profile not in ("mixed", "faults", "api", "repair"):
        raise ValueError(f"unknown chaos profile {profile!r}")
    rng = random.Random(seed)
    informer = rng.random() < 0.7
    jobs = rng.randint(1, 3)
    workloads = []
    for i in range(jobs):
        shape = rng.choice(GANG_SHAPES)
        if profile == "repair" and i == 0:
            # Guarantee a multi-host victim for the host failure.
            shape = rng.choice(("v5e-16", "v5e-32", "v5p-16"))
        workloads.append(Workload(
            job=f"chaos-{seed}-{i}", shape=shape,
            arrival=rng.uniform(0.0, 120.0),
            completion_prob=rng.choice((0.0, 0.0, 0.01)),
            pinned=rng.random() < 0.6))

    api_chaos = profile in ("mixed", "api")
    fault_chaos = profile in ("mixed", "faults", "repair")
    events: list[Event] = []

    def fire(probability: float) -> bool:
        return rng.random() < probability

    if api_chaos and fire(0.5):
        start = rng.uniform(60.0, 260.0)
        events.append(Event(start, "brownout",
                            {"duration": rng.uniform(15.0, 60.0)}))
    if api_chaos and informer and fire(0.4):
        events.append(Event(rng.uniform(30.0, 300.0), "watch_storm",
                            {"count": rng.randint(20, 60)}))
    if api_chaos and informer and fire(0.4):
        for _ in range(rng.randint(1, 3)):
            events.append(Event(rng.uniform(30.0, 300.0), "flood_410"))
    if fault_chaos and fire(0.5):
        start = rng.uniform(0.0, 200.0)
        events.append(Event(start, "stockout",
                            {"duration": rng.uniform(30.0, 120.0)}))
    if fault_chaos and fire(0.35):
        events.append(Event(rng.uniform(20.0, 260.0),
                            "mid_provision_stockout"))
    if fault_chaos and fire(0.3):
        events.append(Event(rng.uniform(150.0, 330.0), "preempt"))
    if profile == "repair" or (fault_chaos and fire(0.5)):
        events.append(Event(
            rng.uniform(150.0, 330.0), "host_fail",
            {"mode": rng.choice(("notready", "delete"))}))

    events.sort(key=lambda e: e.t)
    last = max([e.t + e.args.get("duration", 0.0) for e in events],
               default=0.0)
    until = max(last, 120.0) + QUIET_TAIL
    return ScenarioProgram(
        seed=seed, step=5.0, until=until, settle=600.0,
        workloads=tuple(workloads), events=tuple(events),
        informer=informer,
        provision_delay=rng.choice((10.0, 30.0, 60.0)),
        stagger_seconds=rng.choice((0.0, 0.0, 5.0)),
        max_total_chips=rng.choice((256, 1024)))
