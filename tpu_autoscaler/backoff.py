"""Shared retry-backoff arithmetic.

One formula for every REST layer (k8s/client.py, actuators/gcp.py):
Retry-After wins when the server said it — capped, because an unbounded
server hint must not park a single-threaded control loop (or outlive a
leader lease) — else exponential with full jitter.  The retry LOOPS
stay with their owners (they genuinely differ: GCP re-resolves tokens
on 401, the kube client treats DELETE-404 as success); only the
drift-prone math lives here.
"""

from __future__ import annotations

#: Watch-reconnect schedule, shared by controller/watch.py
#: (WatchTrigger) and k8s/informer.py (ResourceWatch) so the two watch
#: loops can never drift apart on tuning.
WATCH_BACKOFF_BASE_S = 1.0
WATCH_BACKOFF_CAP_S = 60.0

#: Cloud-REST retry schedule, shared by actuators/gcp.py (GcpRest's
#: blocking loop) and actuators/executor.py (ActuationExecutor's
#: reschedule-at-retry_at path) so the serial and pipelined dispatch
#: modes back off identically for the same failure.
REST_BACKOFF_BASE_S = 0.5
REST_BACKOFF_CAP_S = 8.0
#: A server-sent Retry-After is honored up to this multiple of the cap
#: (longer hints must not park a provision past its dispatch deadline).
REST_RETRY_AFTER_CAP_FACTOR = 4.0


def watch_backoff_seconds(failure_streak: int, rng) -> float:
    """Watch-reconnect delay: exponential with full jitter,
    uniform(0, min(cap, base * 2^(streak-1)))."""
    return backoff_seconds(
        max(0, failure_streak - 1), None,
        base_s=WATCH_BACKOFF_BASE_S, cap_s=WATCH_BACKOFF_CAP_S,
        retry_after_cap_s=WATCH_BACKOFF_CAP_S, rng=rng)


def backoff_seconds(attempt: int, retry_after, *, base_s: float,
                    cap_s: float, retry_after_cap_s: float, rng) -> float:
    if retry_after is not None:
        try:
            return min(float(retry_after), retry_after_cap_s)
        except (TypeError, ValueError):
            pass
    return rng.uniform(0, min(cap_s, base_s * 2 ** attempt))
