"""In-memory per-slice timers feeding the state machine.

The reference tracked per-node idle/launch times implicitly (EC2-ancestor
pattern); this tracker is explicit and injectable-clock so tests control
time.  It is deliberately *crash-only* (SURVEY.md §6.3): state lives only in
memory, and on restart timers restart — which can only delay scale-down,
never wrongly accelerate it.  Cordons we initiate are additionally stamped
on the node via an annotation so a restarted process still knows a DRAINING
slice is ours.
"""

from __future__ import annotations

import dataclasses

from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.state.machine import SliceView
from tpu_autoscaler.topology.catalog import shape_from_selectors

# Annotation stamped on nodes we cordon, so drain ownership survives
# process restarts (the one piece of state the crash-only design persists,
# and it lives in the cluster, not in us).
DRAIN_ANNOTATION = "autoscaler.tpu.dev/draining"


@dataclasses.dataclass
class _SliceTimes:
    all_ready_since: float | None = None
    idle_since: float | None = None
    we_cordoned: bool = False


class SliceTracker:
    def __init__(self):
        self._times: dict[str, _SliceTimes] = {}

    def note_cordoned(self, slice_id: str) -> None:
        self._times.setdefault(slice_id, _SliceTimes()).we_cordoned = True

    def forget(self, slice_id: str) -> None:
        self._times.pop(slice_id, None)

    def all_ready_since(self, slice_id: str) -> float | None:
        """When the slice's readiness barrier cleared (None if it never
        has this process lifetime) — feeds bind_latency_seconds."""
        t = self._times.get(slice_id)
        return t.all_ready_since if t else None

    def observe(self, slice_id: str, nodes: list[Node], pods: list[Pod],
                now: float) -> SliceView:
        """Update timers from one observation and produce a SliceView."""
        t = self._times.setdefault(slice_id, _SliceTimes())

        all_ready = bool(nodes) and all(n.is_ready for n in nodes)
        expected_hosts = None
        if nodes and nodes[0].is_tpu:
            # Hosts of a multi-host slice register gradually; until the
            # count matches the shape's host count the barrier holds even
            # if every host seen SO FAR is Ready (a 1-of-64-registered
            # v5p-256 is not a usable slice).  The expected count comes
            # from the accelerator/topology labels every GKE TPU node
            # carries; an unknown shape falls back to observed-only.
            try:
                shape = shape_from_selectors(nodes[0].labels)
            except KeyError:
                shape = None
            if shape is not None:
                expected_hosts = shape.hosts
                if len(nodes) < shape.hosts:
                    all_ready = False
        if all_ready and t.all_ready_since is None:
            t.all_ready_since = now

        view = SliceView(
            slice_id=slice_id, nodes=nodes, pods=pods, now=now,
            all_ready_since=t.all_ready_since, idle_since=t.idle_since,
            we_cordoned=t.we_cordoned or any(
                DRAIN_ANNOTATION in n.annotations for n in nodes),
            expected_hosts=expected_hosts,
        )
        has_workload = bool(view.workload_pods)
        if has_workload:
            t.idle_since = None
        elif t.idle_since is None and all_ready:
            t.idle_since = now
        # Refresh the view with post-update idle time.
        view.idle_since = t.idle_since
        return view

    def known_slices(self) -> list[str]:
        return list(self._times)
