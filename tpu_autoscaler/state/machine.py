"""Slice classification: the scale-down state machine.

Reference parity (cluster.py §ClusterNodeState, §Cluster.maintain), with the
unit of classification changed from node to slice and two TPU-specific
additions:

- PROVISIONING: the multi-host readiness barrier — a v5e-64 slice is usable
  only once all 16 hosts register Ready (SURVEY.md §8 hard parts); the
  reference's per-VM "launch grace period" becomes this barrier plus
  LAUNCH_GRACE after it clears.
- DRAINING / checkpoint-awareness: a busy slice being reclaimed (spot
  preemption, scale-to-zero) signals the job and waits a bounded time for a
  checkpoint before evicting (BASELINE config #5); see
  ``tpu_autoscaler.controller``.

CPU nodes run through the same machine as single-node "slices", which is
exactly the degenerate case the reference handled.
"""

from __future__ import annotations

import dataclasses
import enum


from tpu_autoscaler.k8s.objects import Node, Pod


class SliceState(str, enum.Enum):
    # Requested from the actuator; not all hosts Ready yet (barrier).
    PROVISIONING = "provisioning"
    # All hosts Ready, within the post-launch grace window.
    LAUNCH_GRACE = "launch-grace"
    # Workload pods running somewhere on the slice.
    BUSY = "busy"
    # Busy, but below the utilization threshold with all pods movable —
    # candidate for consolidation (reference: UNDER_UTILIZED_DRAINABLE;
    # CPU units only, a TPU job always owns its whole slice).
    UNDER_UTILIZED = "under-utilized"
    # No workload pods; idle shorter than the idle threshold.
    IDLE = "idle"
    # Idle beyond threshold and eligible to reclaim.
    IDLE_DRAINABLE = "idle-drainable"
    # Idle but retained by spare/warm policy.
    SPARE = "spare"
    # Cordoned by us; waiting for evictions/checkpoint before delete.
    DRAINING = "draining"
    # Cordoned by someone else: hands off (reference IDLE_UNSCHEDULABLE).
    UNSCHEDULABLE = "unschedulable"
    # A host went NotReady after the slice was up: broken ICI domain.
    UNHEALTHY = "unhealthy"


@dataclasses.dataclass
class SliceView:
    """Everything the classifier needs to know about one slice."""

    slice_id: str
    nodes: list[Node]
    pods: list[Pod]                  # pods bound to any host of the slice
    now: float                       # seconds (monotonic-ish epoch)
    all_ready_since: float | None    # tracker: when the barrier cleared
    idle_since: float | None         # tracker: when it last became workload-free
    we_cordoned: bool                # tracker: drain initiated by us
    # Catalog host count for the slice's shape (None for CPU units or
    # unknown shapes).  A slice observed with FEWER hosts than expected
    # after its barrier once cleared lost a node object outright — a
    # broken ICI domain that looks healthy host-by-host (ISSUE 7).
    expected_hosts: int | None = None

    @property
    def workload_pods(self) -> list[Pod]:
        """Pods that make a slice busy: everything except daemonsets and
        mirror pods (reference: cluster.py busy/idle input set)."""
        return [p for p in self.pods if p.is_workload]

    @property
    def utilization(self) -> float:
        """Max over cpu/memory of requested/allocatable across the unit."""
        used_cpu = used_mem = alloc_cpu = alloc_mem = 0.0
        for n in self.nodes:
            alloc_cpu += n.allocatable.get("cpu")
            alloc_mem += n.allocatable.get("memory")
        for p in self.workload_pods:
            used_cpu += p.resources.get("cpu")
            used_mem += p.resources.get("memory")
        fracs = [used / alloc for used, alloc in
                 ((used_cpu, alloc_cpu), (used_mem, alloc_mem)) if alloc > 0]
        return max(fracs) if fracs else 0.0

    @property
    def all_workload_drainable(self) -> bool:
        return all(p.is_drainable for p in self.workload_pods)


def classify_slice(view: SliceView, *, grace_seconds: float,
                   idle_threshold_seconds: float,
                   spare: bool = False,
                   utilization_threshold: float = 0.0) -> SliceState:
    """Classify one slice. Pure function: all time comes in via the view."""
    nodes = view.nodes
    # A drain we initiated takes precedence over everything, including
    # health: an UNHEALTHY slice being reclaimed must classify DRAINING so
    # the drain can complete and the hardware actually gets deleted.
    if view.we_cordoned and any(n.unschedulable for n in nodes):
        return SliceState.DRAINING

    missing_host = (view.expected_hosts is not None
                    and 0 < len(nodes) < view.expected_hosts)
    if (not all(n.is_ready for n in nodes) or missing_host
            or view.all_ready_since is None):
        # Never fully Ready -> still behind the provisioning barrier; a
        # previously-Ready slice with a NotReady host — or one whose
        # host's Node object was DELETED out from under it (same broken
        # ICI domain, invisible to per-host readiness) — is broken
        # hardware.
        if view.all_ready_since is not None:
            return SliceState.UNHEALTHY
        return SliceState.PROVISIONING

    if any(n.unschedulable for n in nodes):
        return SliceState.UNSCHEDULABLE

    if view.workload_pods:
        past_grace = view.now - view.all_ready_since >= grace_seconds
        if (utilization_threshold > 0.0
                and past_grace
                and not any(n.is_tpu for n in nodes)
                and view.all_workload_drainable
                and view.utilization < utilization_threshold):
            return SliceState.UNDER_UTILIZED
        return SliceState.BUSY

    if view.now - view.all_ready_since < grace_seconds:
        return SliceState.LAUNCH_GRACE

    idle_for = view.now - (view.idle_since
                           if view.idle_since is not None
                           else view.all_ready_since)
    if idle_for < idle_threshold_seconds:
        return SliceState.IDLE
    if spare:
        return SliceState.SPARE
    return SliceState.IDLE_DRAINABLE
