"""Per-slice state machine (scale-down policy).

Analog of the reference's cluster.py §ClusterNodeState machine
(INSTANCE_TERMINATED / SPARE_AGENT / GRACE_PERIOD / BUSY / IDLE_* /
UNDER_UTILIZED_*), re-derived per-slice: grace, idle, drain, and delete all
operate on whole ICI slices so a running pjit/pmap job is never bisected
(SURVEY.md §8 "slice-atomic semantics").
"""

from tpu_autoscaler.state.machine import SliceState, SliceView, classify_slice
from tpu_autoscaler.state.tracker import SliceTracker

__all__ = ["SliceState", "SliceTracker", "SliceView", "classify_slice"]
