"""The Repacker: stateful wrapper over the pure repack algebra.

Consulted once per reconcile pass by the Reconciler (crash-only, like
the PolicyEngine and ServingScaler): candidate rows in, bounded
:class:`~tpu_autoscaler.repack.policy.MigrationPlan` decisions out.
The migration *lifecycle* (cordon + checkpoint drain, advisory
replacement, trace spans) is the Reconciler's — it already owns the
identical repair pipeline — so this class keeps only what outlives a
pass:

- the rolling migration-cost budget (committed projected costs of
  in-flight migrations + realized costs of closed ones, trimmed and
  summed by policy/slo.py ``budget_remaining`` — the ONE window
  algebra shared with the prewarm waste budget);
- per-gang cooldowns (anti-thrash: a migrated gang is left alone);
- cumulative savings/cost totals (the chaos corpus'
  never-net-negative-savings invariant reads them) and a bounded ring
  of recent closes for ``/debugz/repack`` and ``repack-report``.

Threading: reconcile-thread-only writes; ``debug_state()`` copies with
the established bounded-retry pattern for the /debugz thread.
"""

from __future__ import annotations

import collections
import logging
from typing import Any, Iterable, Mapping, Sequence

from tpu_autoscaler.cost.pricebook import PriceBook
from tpu_autoscaler.policy.slo import budget_remaining
from tpu_autoscaler.repack.policy import (
    MigrationPlan,
    RepackConfig,
    UnitRow,
    plan_candidates,
    realized_attribution,
    should_abort,
)
from tpu_autoscaler.units import ChipSeconds, Seconds, UsdPerChipHour

log = logging.getLogger(__name__)

#: Closed migrations retained for the report surfaces.
RECENT_CLOSES = 64


class Repacker:
    """Per-pass repack advice + budget bookkeeping."""

    def __init__(self, config: RepackConfig | None = None,
                 price_book: PriceBook | None = None) -> None:
        self.config = config or RepackConfig()
        self.price_book = price_book or PriceBook()
        self._metrics: Any = None
        # Rolling budget events: (t, chip-seconds charged).  Projected
        # costs are charged at decision time and trued up at close —
        # a string of expensive migrations exhausts the window and the
        # repacker self-mutes, exactly like the prewarm budget.
        self._budget_events: list[tuple[Seconds, ChipSeconds]] = []
        # gang key -> cooldown expiry.
        self._cooldowns: dict[tuple, Seconds] = {}
        self._last_rejections: list[str] = []
        self.recent: collections.deque[dict[str, Any]] = \
            collections.deque(maxlen=RECENT_CLOSES)
        # Cumulative realized totals (net may go negative on a misfire
        # — the chaos invariant asserts it never does end-to-end).
        self.totals = {"started": 0, "completed": 0, "aborted": 0,
                       "abandoned": 0, "misfires": 0,
                       "realized_cost_cs": 0.0, "saved_cs": 0.0,
                       "saved_usd": 0.0, "net_cs": 0.0}

    def bind(self, metrics: Any = None) -> None:
        if metrics is not None:
            self._metrics = metrics

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None and by:
            self._metrics.inc(name, by)

    def set_gauge(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(name, value)

    # -- rates ------------------------------------------------------------

    def rate(self, accel: str, tier: str) -> UsdPerChipHour:
        return self.price_book.rate(accel, tier)[0]

    # -- the per-pass entry points ----------------------------------------

    def settle(self, now: Seconds) -> ChipSeconds:
        """Trim the rolling budget window and export its gauge; returns
        the remaining budget.  Called every repack pass (advise may be
        skipped when the fleet has no candidates — the gauge must not
        go stale)."""
        cfg = self.config
        self._budget_events, spent, remaining = budget_remaining(
            self._budget_events, now, cfg.budget_window_seconds,
            cfg.budget_chip_seconds)
        self.set_gauge("repack_budget_spent_chip_seconds", round(spent, 3))
        return remaining

    def advise(self, rows: Sequence[UnitRow],
               idle_spot_chips: Mapping[str, int], now: Seconds, *,
               active_migrations: int,
               excluded: Iterable[str] = (),
               burning_pools: Iterable[str] = (),
               rightsize_targets: Mapping[str, tuple[str, int]]
               | None = None,
               ) -> list[MigrationPlan]:
        """This pass's migration decisions (possibly empty)."""
        cfg = self.config
        remaining = self.settle(now)
        plans, rejections = plan_candidates(
            rows, idle_spot_chips, self.rate, now, cfg,
            active_migrations=active_migrations,
            budget_remaining_cs=remaining,
            excluded=frozenset(excluded),
            burning_pools=frozenset(burning_pools),
            rightsize_targets=rightsize_targets)
        if remaining <= 0.0 and rows:
            self._inc("repack_budget_muted")
        self._last_rejections = rejections[:32]
        self.set_gauge("repack_candidates", len(plans) + len(rejections))
        return plans

    def gang_cooled(self, keys: Iterable[tuple],
                    now: Seconds) -> bool:
        """True while ANY of the gang keys is inside its cooldown."""
        return any(now < self._cooldowns.get(k, 0.0) for k in keys)

    def guard(self, plan: MigrationPlan, now: Seconds, *,
              started: Seconds, realized_cost_cs: ChipSeconds,
              destination_available: bool,
              provision_pending: bool) -> str | None:
        """In-flight verdict for one migration (None = keep going)."""
        return should_abort(
            plan, self.config, realized_cost_cs=realized_cost_cs,
            elapsed=now - started,
            destination_available=destination_available,
            provision_pending=provision_pending)

    # -- lifecycle notes (called by the Reconciler) ------------------------

    def note_started(self, plan: MigrationPlan,
                     gang_keys: Sequence[tuple],
                     now: Seconds) -> None:
        # Commit the projected cost against the rolling window NOW —
        # waiting for the close would let a burst of decisions in one
        # pass all see the un-charged budget (the prewarm lesson).
        self._budget_events.append((now, plan.projected_cost_cs))
        for key in gang_keys:
            self._cooldowns[key] = now + self.config.gang_cooldown_seconds
        self.totals["started"] += 1
        self._inc("repack_migrations_started")

    def _true_up(self, plan: MigrationPlan,
                 realized_cost_cs: ChipSeconds,
                 now: Seconds) -> None:
        """Replace the committed projection with the realized cost (the
        projection was charged at start; drop it, charge reality)."""
        for i in range(len(self._budget_events) - 1, -1, -1):
            if self._budget_events[i][1] == plan.projected_cost_cs:
                del self._budget_events[i]
                break
        self._budget_events.append((now, realized_cost_cs))
        self.totals["realized_cost_cs"] += realized_cost_cs
        self._inc("repack_migration_cost_chip_seconds",
                  realized_cost_cs)

    def note_completed(self, plan: MigrationPlan, now: Seconds, *,
                       realized_cost_cs: ChipSeconds,
                       landed_rate: UsdPerChipHour | None
                       ) -> dict[str, float]:
        """Close the books on a completed migration; returns the
        attribution dict stamped on the closing ``repack`` trace."""
        attrs = realized_attribution(
            plan, self.config, realized_cost_cs=realized_cost_cs,
            landed_rate=landed_rate)
        self._true_up(plan, realized_cost_cs, now)
        net_cs = attrs["chip_seconds_saved"]
        net_usd = attrs["dollar_proxy_saved"]
        self.totals["completed"] += 1
        self.totals["net_cs"] += net_cs
        self._inc("repack_migrations_completed")
        if net_cs > 0.0:
            self.totals["saved_cs"] += net_cs
            self._inc("repack_chip_seconds_saved", net_cs)
        elif net_cs < 0.0:
            # Strictly negative only: a zero-net close is neither a
            # saving nor a loss, and the chaos misfire-surfacing
            # invariant counts exactly the net-NEGATIVE traces.
            self.totals["misfires"] += 1
            self._inc("repack_misfires")
        if net_usd > 0.0:
            self.totals["saved_usd"] += net_usd
            self._inc("repack_dollar_proxy_saved", net_usd)
        self.set_gauge("repack_net_chip_seconds_saved",
                    round(self.totals["net_cs"], 3))
        self.recent.append({"unit": plan.unit_id, "kind": plan.kind,
                            "outcome": "completed", "t": now, **attrs})
        return attrs

    def note_closed(self, plan: MigrationPlan, now: Seconds, *,
                    outcome: str, realized_cost_cs: ChipSeconds,
                    reason: str = "") -> None:
        """An aborted or abandoned migration: realized cost is real
        money, savings are zero — the net gauge carries the hit (the
        budget guard's job is keeping that hit bounded)."""
        self._true_up(plan, realized_cost_cs, now)
        self.totals[outcome] = self.totals.get(outcome, 0) + 1
        self.totals["net_cs"] -= realized_cost_cs
        self._inc(f"repack_migrations_{outcome}")
        self.set_gauge("repack_net_chip_seconds_saved",
                    round(self.totals["net_cs"], 3))
        self.recent.append({"unit": plan.unit_id, "kind": plan.kind,
                            "outcome": outcome, "t": now,
                            "migration_cost_chip_seconds":
                                round(realized_cost_cs, 3),
                            "reason": reason})

    # -- introspection ----------------------------------------------------

    def debug_state(self) -> dict[str, Any]:
        """The ``/debugz/repack`` body's repacker half (the Reconciler
        adds the live in-flight table).  Bounded-retry copy: the
        /debugz thread reads while the reconcile thread mutates."""
        import dataclasses

        for _ in range(5):
            try:
                return {
                    "config": dataclasses.asdict(self.config),
                    "totals": dict(self.totals),
                    "budget": {
                        "window_seconds":
                            self.config.budget_window_seconds,
                        "budget_chip_seconds":
                            self.config.budget_chip_seconds,
                        "events": [[t, round(w, 3)] for t, w
                                   in list(self._budget_events)],
                    },
                    "recent": list(self.recent),
                    "last_rejections": list(self._last_rejections),
                }
            except RuntimeError:  # mutated mid-copy; retry
                continue
        return {"unavailable": "mutating"}
