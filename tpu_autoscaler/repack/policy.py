"""Repack algebra: which gangs are wrongly placed, and is moving them
worth it (ISSUE 12, docs/REPACK.md).

The SLO-driven cost-aware autoscaling paper optimizes placement cost at
admission; this module is the pure half of doing it *continuously*.
Inputs are the cost ledger's per-unit placement rows
(``CostLedger.placement_quality``) and price-book rates; outputs are
:class:`MigrationPlan` decisions plus human-readable rejections, so a
silent repacker is still an explainable one (the decide_prewarms
pattern, policy/slo.py).

Two migration kinds, matching the fragmentation scorer's recoverable
components (cost/frag.py):

- ``displace`` — a gang runs on expensive-tier chips while a same-shape
  SPOT unit sits idle: drain the source slice and the advisory
  replacement (the unit's own shape) is satisfied by the idle spot
  slice without provisioning — the gang runs identically for a
  fraction of the $-proxy, and the expensive slice is released whole;
- ``rightsize`` — a gang requests fewer chips than its slice carries
  (topology-poor placement): the advisory replacement names the
  fitter's right-sized shape, and the oversized slice is released.

**The budget algebra.**  Everything is measured in chip-seconds — the
PR 8 wasted-chip-seconds currency — so the repack budget, the policy
waste budget, and the ledger speak one unit:

- a migration's *cost* is the chip-seconds its source unit burns in
  the ``repair`` state (cordon + checkpoint drain) plus, for
  rightsize, the replacement's provisioning chip-seconds;
- its *savings rate* converts the $-proxy delta back into
  chip-second-equivalents at the source rate
  (``chips x (1 - rate_new/rate_old)`` per second for displace; the
  freed chips per second for rightsize), projected over
  ``savings_horizon_seconds``;
- admission requires ``projected savings >= min_savings_ratio x
  projected cost`` AND headroom in the rolling repack budget
  (policy/slo.py ``budget_remaining`` — the ONE window algebra);
- the in-flight guard re-evaluates every pass with REALIZED cost and
  CURRENT destination availability: the moment projected cost exceeds
  projected savings (``abort_savings_ratio``), the migration aborts —
  repacking can never cost more than it saves, by construction.

Pure computation over injected values only (the policy/slo.py
contract): no clocks, no controller state, no I/O.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping, Sequence

from tpu_autoscaler.units import (
    Chips,
    ChipSeconds,
    Fraction,
    Seconds,
    Usd,
    UsdPerChipHour,
    chip_seconds,
    usd,
)

#: Migration kinds, in candidate-ranking order (displacement first:
#: same chips for a fraction of the price beats freeing chips that
#: must be re-provisioned elsewhere to matter).
KINDS = ("displace", "rightsize")


@dataclasses.dataclass(frozen=True)
class RepackConfig:
    """Knobs of the repack algebra (docs/REPACK.md)."""

    # At most this many concurrent in-flight migrations fleet-wide —
    # repacking is background work; it must never look like an outage.
    max_concurrent_migrations: int = 1
    # Admission bar: projected savings must exceed projected cost by
    # this factor (headroom for drain overruns and landing slop).
    min_savings_ratio: Fraction = 2.0
    # In-flight abort bar: the migration aborts the moment projected
    # total cost x this ratio exceeds projected savings.  1.0 = abort
    # exactly when the move stops paying.
    abort_savings_ratio: Fraction = 1.0
    # Horizon the savings rate is projected over.  A gang that leaves
    # sooner realizes less than projected — the min_savings_ratio
    # margin and the never-worse bench gate absorb that.
    savings_horizon_seconds: Seconds = 3600.0
    # Rolling migration-cost budget: committed projected costs of
    # in-flight migrations plus realized costs of closed ones, per
    # window (the PR 8 waste-budget shape; policy/slo.py).
    budget_chip_seconds: ChipSeconds = 50_000.0
    budget_window_seconds: Seconds = 3600.0
    # Cost-estimate terms: how long the source burns in the repair
    # state, and the replacement provision estimate (rightsize only).
    drain_estimate_seconds: Seconds = 120.0
    provision_estimate_seconds: Seconds = 240.0
    # A unit must have been busy this long before it is a candidate —
    # migrating a gang that just landed is thrash, not savings.
    min_dwell_seconds: Seconds = 600.0
    # After any migration (completed, aborted or abandoned) touches a
    # gang, that gang is left alone this long.
    gang_cooldown_seconds: Seconds = 1800.0
    # Serving pools below this SLO attainment are never migrated —
    # a burning pool needs its replicas where they are
    # (serving/adapter.py ``burning_pools``).
    slo_attainment_floor: Fraction = 0.95


@dataclasses.dataclass(frozen=True)
class UnitRow:
    """One busy unit's placement row (CostLedger.placement_quality)."""

    unit_id: str
    pool: str
    accel: str
    tier: str
    shape: str | None
    chips: Chips
    used_chips: Chips
    state: str                 # "serving" | "training"
    since: Seconds             # current busy span entered
    gang_id: str | None


@dataclasses.dataclass(frozen=True)
class MigrationPlan:
    """One approved migration: drain ``unit_id``, let the advisory
    replacement (``target_shape``) re-home the gang."""

    unit_id: str
    kind: str                  # "displace" | "rightsize"
    pool: str
    accel: str
    tier: str
    shape: str                 # source shape
    target_shape: str
    chips: Chips               # source unit chips
    target_chips: Chips
    rate_src: UsdPerChipHour   # at the source
    rate_dst: UsdPerChipHour   # projected at the target
    freed_cs_per_s: float      # chip-second-equivalents saved per second
    saved_usd_per_s: float
    projected_cost_cs: ChipSeconds
    projected_saving_cs: ChipSeconds
    reason: str


def projected_cost_cs(kind: str, chips: Chips, target_chips: Chips,
                      cfg: RepackConfig) -> ChipSeconds:
    """Chip-seconds a migration is expected to burn: the source holds
    ``chips`` through the drain; a rightsize also pays the
    replacement's provisioning chip-seconds."""
    cost = chip_seconds(chips, cfg.drain_estimate_seconds)
    if kind == "rightsize":
        cost += chip_seconds(target_chips,
                             cfg.provision_estimate_seconds)
    return cost


def saving_rate(kind: str, chips: Chips, target_chips: Chips,
                rate_src: UsdPerChipHour, rate_dst: UsdPerChipHour
                ) -> tuple[float, float]:
    """(chip-second-equivalents per second, $ per second) a completed
    migration saves.  Displacement keeps the chips and drops the rate
    (``chips x (1 - rate_dst/rate_src)``); rightsize frees chips
    outright."""
    if kind == "rightsize":
        freed = max(0, chips - target_chips)
        return float(freed), freed * rate_src / 3600.0
    if rate_src <= 0.0:
        return 0.0, 0.0
    saved_usd = chips * max(0.0, rate_src - rate_dst) / 3600.0
    return chips * max(0.0, 1.0 - rate_dst / rate_src), saved_usd


def plan_candidates(rows: Sequence[UnitRow],
                    idle_spot_chips: Mapping[str, int],
                    rate: Callable[[str, str], UsdPerChipHour],
                    now: Seconds, cfg: RepackConfig, *,
                    active_migrations: int,
                    budget_remaining_cs: ChipSeconds,
                    excluded: frozenset[str] = frozenset(),
                    burning_pools: frozenset[str] = frozenset(),
                    rightsize_targets: Mapping[str, tuple[str, int]]
                    | None = None,
                    ) -> tuple[list[MigrationPlan], list[str]]:
    """The admission gate.  Returns ``(plans, rejections)``.

    ``rightsize_targets`` maps unit id -> (target shape, target chips)
    for overprovisioned units the caller's fitter already right-sized
    (the fitter is the one authority on what a gang actually needs —
    this module never second-guesses it).  ``excluded`` carries every
    unit the caller ruled out mechanically (under repair/drain, policy
    holds, multislice members, pending gangs, cooldowns); economic
    rejections are produced here so the two layers never disagree on
    whose "no" it was.
    """
    plans: list[MigrationPlan] = []
    rejections: list[str] = []
    rightsize_targets = rightsize_targets or {}
    slots = cfg.max_concurrent_migrations - active_migrations
    committed: ChipSeconds = 0.0
    # Idle spot is consumed as displacements are planned: two same-
    # shape candidates must not both count the one idle slice.
    spot_left = dict(idle_spot_chips)

    candidates: list[tuple[float, UnitRow, str, str, int,
                           float, float]] = []
    for row in rows:
        if row.unit_id in excluded or row.shape is None:
            continue
        if row.pool in burning_pools:
            rejections.append(
                f"{row.unit_id}: pool {row.pool} is SLO-burning — "
                f"replicas stay where they are")
            continue
        if now - row.since < cfg.min_dwell_seconds:
            rejections.append(
                f"{row.unit_id}: busy only {now - row.since:.0f}s "
                f"(< min dwell {cfg.min_dwell_seconds:g}s)")
            continue
        rate_src = rate(row.accel, row.tier)
        rate_spot = rate(row.accel, "spot")
        if row.tier != "spot" and rate_src > rate_spot \
                and spot_left.get(row.shape, 0) >= row.chips:
            freed, _ = saving_rate("displace", row.chips, row.chips,
                                   rate_src, rate_spot)
            candidates.append((freed, row, "displace", row.shape,
                               row.chips, rate_src, rate_spot))
            continue
        target = rightsize_targets.get(row.unit_id)
        if target is not None and target[1] < row.chips:
            freed, _ = saving_rate("rightsize", row.chips, target[1],
                                   rate_src, rate_src)
            candidates.append((freed, row, "rightsize", target[0],
                               target[1], rate_src, rate_src))

    # Biggest saving rate first; unit id breaks ties deterministically.
    candidates.sort(key=lambda c: (-c[0], c[1].unit_id))
    for freed, row, kind, target_shape, target_chips, rate_src, \
            rate_dst in candidates:
        if kind == "displace" \
                and spot_left.get(row.shape, 0) < row.chips:
            rejections.append(
                f"{row.unit_id}: idle spot {row.shape} already "
                f"claimed by a higher-saving displacement this pass")
            continue
        cost = projected_cost_cs(kind, row.chips, target_chips, cfg)
        saving = freed * cfg.savings_horizon_seconds
        _freed, usd_per_s = saving_rate(kind, row.chips, target_chips,
                                        rate_src, rate_dst)
        if saving < cfg.min_savings_ratio * cost:
            rejections.append(
                f"{row.unit_id}: {kind} saves {saving:.0f} chip-s over "
                f"the horizon vs {cost:.0f} projected cost — below the "
                f"{cfg.min_savings_ratio:g}x admission bar")
            continue
        if committed + cost > budget_remaining_cs:
            rejections.append(
                f"{row.unit_id}: {kind} cost {cost:.0f} chip-s would "
                f"blow the rolling repack budget "
                f"({budget_remaining_cs:.0f} remaining)")
            continue
        if slots <= 0:
            rejections.append(
                f"{row.unit_id}: max_concurrent_migrations "
                f"({cfg.max_concurrent_migrations}) reached")
            continue
        slots -= 1
        committed += cost
        if kind == "displace":
            spot_left[row.shape] = spot_left.get(row.shape, 0) \
                - row.chips
            reason = (f"gang on {row.tier} {row.shape} "
                      f"(${rate_src:g}/chip-h) while same-shape spot "
                      f"sits idle (${rate_dst:g}/chip-h)")
        else:
            reason = (f"gang uses {row.used_chips} of {row.chips} "
                      f"chips on {row.shape}; {target_shape} fits it "
                      f"({row.chips - target_chips} chips freed)")
        plans.append(MigrationPlan(
            unit_id=row.unit_id, kind=kind, pool=row.pool,
            accel=row.accel, tier=row.tier, shape=row.shape,
            target_shape=target_shape, chips=row.chips,
            target_chips=target_chips, rate_src=rate_src,
            rate_dst=rate_dst, freed_cs_per_s=freed,
            saved_usd_per_s=usd_per_s, projected_cost_cs=cost,
            projected_saving_cs=saving, reason=reason))
    return plans, rejections


def should_abort(plan: MigrationPlan, cfg: RepackConfig, *,
                 realized_cost_cs: ChipSeconds, elapsed: Seconds,
                 destination_available: bool,
                 provision_pending: bool) -> str | None:
    """The in-flight budget guard: one stateless verdict per pass.

    Returns the abort reason, or None while the migration still pays.
    ``destination_available`` is the caller's CURRENT view (idle spot
    still free for displace, or the gang already landing); a displace
    whose destination vanished has zero projected savings and aborts
    immediately.  ``provision_pending`` keeps the rightsize estimate
    honest while the replacement is still in flight.
    """
    if not destination_available:
        return (f"destination gone: no idle spot {plan.shape} left "
                f"and the gang has not landed — projected savings "
                f"collapsed to 0")
    remaining = plan.chips * max(
        0.0, cfg.drain_estimate_seconds - elapsed)
    if plan.kind == "rightsize" and provision_pending:
        remaining += plan.target_chips * cfg.provision_estimate_seconds
    projected_total = realized_cost_cs + remaining
    if cfg.abort_savings_ratio * projected_total \
            > plan.projected_saving_cs:
        return (f"projected migration cost {projected_total:.0f} "
                f"chip-s exceeds projected savings "
                f"{plan.projected_saving_cs:.0f} chip-s "
                f"(realized {realized_cost_cs:.0f})")
    return None


def realized_attribution(plan: MigrationPlan, cfg: RepackConfig, *,
                         realized_cost_cs: ChipSeconds,
                         landed_rate: UsdPerChipHour | None
                         ) -> dict[str, float]:
    """The closing trace's bill: chip-seconds-saved / $-proxy-saved,
    net of the realized migration cost, computed against the tier the
    gang ACTUALLY landed on (``landed_rate``; None = the projected
    destination rate — the ledger never saw the landing)."""
    rate_dst = plan.rate_dst if landed_rate is None else landed_rate
    freed, usd_per_s = saving_rate(plan.kind, plan.chips,
                                   plan.target_chips, plan.rate_src,
                                   rate_dst)
    horizon = cfg.savings_horizon_seconds
    cost_usd: Usd = usd(plan.rate_src, realized_cost_cs)
    return {
        "chip_seconds_saved": round(freed * horizon
                                    - realized_cost_cs, 3),
        "dollar_proxy_saved": round(usd_per_s * horizon - cost_usd, 6),
        "migration_cost_chip_seconds": round(realized_cost_cs, 3),
        "landed_rate_usd_chip_hour": round(rate_dst, 6),
    }
