"""`tpu-autoscaler repack-report`: render the repacker's books.

Input is ``Controller.repack_route()``'s body — fetched live from
``/debugz/repack`` or read from an incident bundle's ``repack``
section.  Pure formatting over dict inputs (CLI wiring in main.py),
exactly like cost/report.py.
"""

from __future__ import annotations

from typing import Any, Mapping


def _fmt_cs(cs: float) -> str:
    if abs(cs) >= 3600.0:
        return f"{cs / 3600.0:.1f} chip-h"
    return f"{cs:.0f} chip-s"


def render_repack(body: Mapping[str, Any]) -> str:
    """The operator view: totals, the rolling budget, in-flight
    migrations, recent closes with their attribution, and why the
    last pass's candidates were turned down."""
    lines: list[str] = []
    totals = body.get("totals", {})
    lines.append(
        f"REPACK REPORT  (migrations: {totals.get('started', 0)} "
        f"started, {totals.get('completed', 0)} completed, "
        f"{totals.get('aborted', 0)} aborted, "
        f"{totals.get('abandoned', 0)} abandoned, "
        f"{totals.get('misfires', 0)} misfires)")
    lines.append(
        f"  net savings:   {_fmt_cs(totals.get('net_cs', 0.0))}  "
        f"(~${totals.get('saved_usd', 0.0):.2f} proxy saved, "
        f"{_fmt_cs(totals.get('realized_cost_cs', 0.0))} migration "
        f"cost)")
    budget = body.get("budget", {})
    if budget:
        spent = sum(w for _t, w in budget.get("events", ()))
        lines.append(
            f"  rolling budget: {_fmt_cs(spent)} committed of "
            f"{_fmt_cs(budget.get('budget_chip_seconds', 0.0))} per "
            f"{budget.get('window_seconds', 0):g}s window")
    active = body.get("active", [])
    lines.append("")
    if active:
        lines.append("in flight:")
        for m in active:
            lines.append(
                f"  {m.get('unit', '?'):<28} {m.get('kind', '?'):<10} "
                f"-> {m.get('target_shape', '?'):<10} "
                f"started t={m.get('started', 0):g}  "
                f"cost so far {_fmt_cs(m.get('realized_cost_cs', 0.0))}"
                f"  projected saving "
                f"{_fmt_cs(m.get('projected_saving_cs', 0.0))}")
    else:
        lines.append("in flight: (none)")
    recent = body.get("recent", [])
    if recent:
        lines.append("")
        lines.append("recent closes (newest last):")
        for m in recent[-10:]:
            attribution = ""
            if m.get("outcome") == "completed":
                attribution = (
                    f"  saved {_fmt_cs(m.get('chip_seconds_saved', 0.0))}"
                    f" / ~${m.get('dollar_proxy_saved', 0.0):.2f}, cost "
                    f"{_fmt_cs(m.get('migration_cost_chip_seconds', 0.0))}")
            elif m.get("reason"):
                attribution = f"  ({m['reason']})"
            lines.append(
                f"  t={m.get('t', 0):<8g} {m.get('unit', '?'):<28} "
                f"{m.get('kind', '?'):<10} {m.get('outcome', '?'):<10}"
                f"{attribution}")
    rejections = body.get("last_rejections", [])
    if rejections:
        lines.append("")
        lines.append("last pass's rejections:")
        for r in rejections[:10]:
            lines.append(f"  {r}")
    if body.get("disabled"):
        lines.append("")
        lines.append("(repacker disabled: --repack not set)")
    return "\n".join(lines)
