"""Cost-aware continuous repacking (ISSUE 12, docs/REPACK.md).

The cost ledger scores the fleet for fragmentation; this package spends
that signal: a background repacker that finds wrongly-placed gangs
(expensive-tier chips while same-shape spot sits idle, topology-poor
oversized slices), generalizes the ISSUE 7 slice-repair pipeline from
"broken slice" to "wrongly-placed gang" — ICI-atomic cordon +
checkpoint drain + advisory replacement through the planner's existing
hook — and keeps every migration under a hard savings budget: a repack
aborts the moment its projected cost exceeds its ledger-attributed
projected savings.

- :mod:`tpu_autoscaler.repack.policy` — the pure algebra (candidates,
  projections, the abort verdict, realized attribution);
- :mod:`tpu_autoscaler.repack.repacker` — the stateful per-pass engine
  (rolling budget, cooldowns, totals);
- :mod:`tpu_autoscaler.repack.report` — the ``repack-report`` CLI
  rendering.

The migration lifecycle itself (drain, advisory demand, traces) lives
in the Reconciler beside the repair pipeline it generalizes.
"""

from tpu_autoscaler.repack.policy import (
    MigrationPlan,
    RepackConfig,
    UnitRow,
    plan_candidates,
    realized_attribution,
    should_abort,
)
from tpu_autoscaler.repack.repacker import Repacker
from tpu_autoscaler.repack.report import render_repack

__all__ = [
    "MigrationPlan",
    "RepackConfig",
    "Repacker",
    "UnitRow",
    "plan_candidates",
    "realized_attribution",
    "render_repack",
    "should_abort",
]
