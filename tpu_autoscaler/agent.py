"""Slice-registration agent for QueuedResource-managed TPU VM fleets.

Closes the unit-id loop the QueuedResource actuator's docstring defines
(actuators/queued_resources.py): slices created through the Cloud TPU
queuedResources API are standalone TPU VM hosts, not GKE nodes — when
those hosts join a cluster the controller manages (self-managed kubelet),
nothing stamps them with the identity labels the controller keys on
(k8s/objects.py::KubeNode.slice_id / .pool and the accelerator/topology
contract from topology/catalog.py).  GKE node pools get these labels
natively from the GKE actuator; QR fleets get them from this agent.

It runs on every TPU VM host (DaemonSet or systemd unit), derives the
slice identity from the TPU VM environment, and level-triggered
re-asserts four labels on its own Node object:

    autoscaler.tpu.dev/slice-id                (SLICE_ID_LABEL)
    autoscaler.tpu.dev/pool                    (POOL_LABEL)
    cloud.google.com/gke-tpu-accelerator       (ACCELERATOR_LABEL)
    cloud.google.com/gke-tpu-topology          (TOPOLOGY_LABEL)

Identity sources, in preference order:

1. Env overrides — ``TPU_AUTOSCALER_SLICE_ID`` / ``TPU_AUTOSCALER_POOL``
   / ``TPU_AUTOSCALER_SHAPE`` (catalog shape name) and ``NODE_NAME``
   (downward API).  The fully-explicit path; what the DaemonSet manifest
   wires.
2. The TPU VM environment: the GCE metadata attribute ``tpu-env``
   (``ACCELERATOR_TYPE: 'v5p-256'`` product naming) resolves the catalog
   shape, and the queuedResources host-naming convention — workers of
   node id ``foo`` are hosts ``foo-w-0`` .. ``foo-w-{n-1}`` — recovers
   the unit id from the hostname, which is exactly the id the actuator's
   ``_unit_owner`` map expects back from the controller.

The patch is a strategic-merge of labels only, issued unconditionally
every interval: blind idempotent assertion needs no read permission, no
local state, and converges after any drift (crash-only, like the
controller itself — SURVEY §6.3).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import re
import socket
import time
import urllib.request

from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    POOL_LABEL,
    SLICE_ID_LABEL,
    SLICE_SHAPES,
    TOPOLOGY_LABEL,
)
from tpu_autoscaler.topology.shapes import SliceShape

log = logging.getLogger(__name__)

METADATA_TPU_ENV = ("http://metadata.google.internal/computeMetadata/v1/"
                    "instance/attributes/tpu-env")

_WORKER_HOST = re.compile(r"^(?P<unit>.+)-w-\d+$")
_TPU_ENV_LINE = re.compile(r"^\s*([A-Z0-9_]+)\s*[:=]\s*(.*?)\s*$")

DEFAULT_POOL = "tpuas"  # matches the actuators' default name_prefix


def unit_id_from_hostname(hostname: str) -> str:
    """QueuedResources name a node id's hosts ``<id>-w-<worker>``; the
    unit id is the hostname minus that suffix.  A hostname without the
    suffix (single-host slice created without workers, or a test box) is
    its own unit id."""
    m = _WORKER_HOST.match(hostname)
    return m.group("unit") if m else hostname


def parse_tpu_env(text: str) -> dict[str, str]:
    """Parse the ``tpu-env`` metadata attribute: one ``KEY: 'value'``
    per line (quotes optional; ``=`` tolerated)."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        m = _TPU_ENV_LINE.match(line)
        if m:
            out[m.group(1)] = m.group(2).strip("'\"")
    return out


def shape_for_product(product: str) -> SliceShape | None:
    """Catalog shape for a Cloud TPU product accelerator name.

    Inverse of the naming the QueuedResource actuator sends
    (``shape.product_name or shape.name``), so identities round-trip
    actuator -> cloud -> tpu-env -> agent."""
    for shape in SLICE_SHAPES.values():
        if (shape.product_name or shape.name) == product:
            return shape
    return None


def fetch_tpu_env(timeout: float = 5.0) -> str | None:
    """GET the tpu-env metadata attribute; None off-GCE (or any error —
    the caller falls back to env/hostname identity)."""
    req = urllib.request.Request(
        METADATA_TPU_ENV, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001 — off-GCE is a normal case
        log.debug("no tpu-env metadata (%s)", e)
        return None


@dataclasses.dataclass(frozen=True)
class AgentIdentity:
    """What the agent will assert about its host."""

    node_name: str
    unit_id: str
    pool: str
    shape: SliceShape | None  # None: stamp identity labels only

    def labels(self) -> dict[str, str]:
        out = {SLICE_ID_LABEL: self.unit_id, POOL_LABEL: self.pool}
        if self.shape is not None:
            out[ACCELERATOR_LABEL] = self.shape.accelerator_type
            out[TOPOLOGY_LABEL] = self.shape.topology_label
        return out


def discover_identity(env: dict | None = None,
                      hostname: str | None = None,
                      tpu_env_text: str | None = None) -> AgentIdentity:
    """Resolve this host's identity (see module docstring for the
    precedence).  ``tpu_env_text`` is injectable for tests; pass the
    result of fetch_tpu_env() in production."""
    env = dict(os.environ if env is None else env)
    hostname = hostname or socket.gethostname().split(".")[0]
    tpu_env = parse_tpu_env(tpu_env_text) if tpu_env_text else {}

    # Node name before pod hostname for the derivation: in the DaemonSet
    # deployment socket.gethostname() is the POD name (no hostNetwork),
    # while NODE_NAME (downward API) is the TPU VM host's name — the one
    # that carries the "<qr-id>-w-<n>" convention.
    node_name = env.get("NODE_NAME") or hostname
    unit_id = env.get("TPU_AUTOSCALER_SLICE_ID") or unit_id_from_hostname(
        node_name)
    pool = env.get("TPU_AUTOSCALER_POOL") or DEFAULT_POOL

    shape: SliceShape | None = None
    shape_name = env.get("TPU_AUTOSCALER_SHAPE")
    if shape_name:
        if shape_name not in SLICE_SHAPES:
            raise ValueError(
                f"TPU_AUTOSCALER_SHAPE={shape_name!r} is not a catalog "
                f"shape; known: {sorted(SLICE_SHAPES)}")
        shape = SLICE_SHAPES[shape_name]
    elif tpu_env.get("ACCELERATOR_TYPE"):
        product = tpu_env["ACCELERATOR_TYPE"]
        shape = shape_for_product(product)
        if shape is None:
            log.warning(
                "tpu-env ACCELERATOR_TYPE %r is not in the catalog; "
                "stamping identity labels only", product)
    return AgentIdentity(node_name=node_name, unit_id=unit_id, pool=pool,
                         shape=shape)


def assert_labels(client, identity: AgentIdentity) -> None:
    """One level-triggered assertion of the identity labels."""
    client.patch_node(identity.node_name,
                      {"metadata": {"labels": identity.labels()}})


def run_agent(client, identity: AgentIdentity, interval: float = 60.0,
              once: bool = False, sleep=time.sleep) -> None:
    """Assert the labels forever (or once).  Failures log and retry on
    the next tick — the Node object may simply not exist yet while the
    kubelet is still registering."""
    log.info("registration agent: node=%s labels=%s",
             identity.node_name, identity.labels())
    while True:
        try:
            assert_labels(client, identity)
        except Exception:  # noqa: BLE001 — crash-only, keep asserting
            log.exception("label assert failed for node %s; will retry",
                          identity.node_name)
        if once:
            return
        # Jitter so a slice's hosts don't synchronize their patches.
        sleep(interval * (1.0 + random.uniform(-0.1, 0.1)))
