"""Actuator interface: provision / delete atomic supply units.

The reference's actuator contract was implicit in EngineScaler (bump ARM
counts, trim resources, one deployment in flight — engine_scaler.py,
deployments.py).  Here it is explicit and asynchronous, mirroring the Cloud
TPU QueuedResource lifecycle (ACCEPTED → PROVISIONING → ACTIVE → FAILED)
that real TPU provisioning exposes; the reconcile loop polls, it never
blocks (SURVEY.md §3.5 "don't block the main loop beyond submission").
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, runtime_checkable

from tpu_autoscaler.engine.planner import InFlight, ProvisionRequest

# Provision lifecycle states (QueuedResource-shaped).
ACCEPTED = "ACCEPTED"
PROVISIONING = "PROVISIONING"
ACTIVE = "ACTIVE"
FAILED = "FAILED"

_IN_FLIGHT = {ACCEPTED, PROVISIONING}


@dataclasses.dataclass
class ProvisionStatus:
    id: str
    request: ProvisionRequest
    state: str
    # Supply-unit ids this provision materialized (1 slice id, or one id
    # per CPU node), known once ACTIVE.
    unit_ids: list[str] = dataclasses.field(default_factory=list)
    error: str | None = None
    # Machine-readable failure category (errors.classify_provision_error:
    # stockout / quota / permission / bad-shape / transient / unknown);
    # set alongside ``error`` by the real actuators.
    reason: str | None = None

    @property
    def in_flight(self) -> bool:
        return self.state in _IN_FLIGHT

    def fail(self, error) -> None:
        """Mark FAILED with the error text and its taxonomy category."""
        from tpu_autoscaler.actuators.errors import classify_provision_error

        self.state = FAILED
        self.error = str(error)
        self.reason = classify_provision_error(error)


@runtime_checkable
class Actuator(Protocol):
    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        """Submit one provisioning action; must return without blocking on
        cloud completion."""
        ...

    def delete(self, unit_id: str) -> None:
        """Tear down one whole supply unit (slice or CPU node) atomically."""
        ...

    def poll(self, now: float) -> None:
        """Advance async provisioning state; called once per reconcile."""
        ...

    def statuses(self) -> list[ProvisionStatus]:
        """All known provisions (in-flight and recently terminal)."""
        ...

    def cancel(self, provision_id: str) -> None:
        """Abort an in-flight provision (stuck in ACCEPTED/PROVISIONING):
        tear down whatever was created and mark the status FAILED so the
        controller's backoff/retry takes over.  Idempotent."""
        ...


def in_flight_of(actuator: Actuator) -> list[InFlight]:
    """Planner's view of an actuator's outstanding work."""
    return [
        InFlight(kind=s.request.kind, shape_name=s.request.shape_name,
                 gang_key=s.request.gang_key, count=s.request.count)
        for s in actuator.statuses() if s.in_flight
    ]
