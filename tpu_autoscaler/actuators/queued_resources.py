"""Cloud TPU QueuedResource actuator (L2 alternative).

Second real actuator, the XPK-style slice-provisioning path: instead of GKE
node pools, slices are requested from the Cloud TPU queued-resources API
(the capacity queue).  Its native lifecycle (ACCEPTED → PROVISIONING →
ACTIVE → FAILED/SUSPENDED) is exactly the ProvisionStatus state set — the
reference's "submit deployment, poll provisioning state" pattern
(deployments.py) maps 1:1, minus the one-in-flight restriction.

Reference-parity role: this is the secondary actuator the way
container_service.py (classic ACS) was secondary to engine_scaler.py.

Scope note: queued resources create *standalone TPU VM slices*, not GKE
nodes — use this actuator for QR-managed fleets where the supply-unit id IS
the queued-resource id, paired with the node-registration agent
(``tpu_autoscaler/agent.py``, ``deploy/agent-daemonset.yaml``) that stamps
SLICE_ID_LABEL with that id on each host's Node object.  For GKE clusters
use ``GkeNodePoolActuator``, whose node pools register labeled nodes
natively.

Actuation pipeline (ISSUE 3, docs/ACTUATION.md):

- With an :class:`~tpu_autoscaler.actuators.executor.ActuationExecutor`
  attached, create POSTs and polls are *dispatched* non-blocking;
  results apply to actuator state only via completion callbacks run by
  ``executor.drain()`` on the reconcile thread (top of
  ``reconcile_once``).  Without one, every call is the old blocking
  round-trip — tests and the bench's serial baseline use that mode.
- Polling is batched: ONE server-side LIST of ``queuedResources`` under
  the parent replaces N per-id GETs.  When LIST is unavailable
  (404/403/400/501 — older API surfaces, restrictive IAM), polling
  falls back to per-id GETs permanently for the process lifetime.
- An id that is gone (per-id GET 404, or absent from
  ``LIST_MISS_THRESHOLD`` consecutive complete LISTs) was deleted out
  of band: that provision is terminally FAILED (reason
  ``deleted-out-of-band``) so its demand can re-provision, instead of
  being re-polled forever as transient.
"""

from __future__ import annotations

import functools
import itertools
import logging
import time

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.actuators.executor import ActuationExecutor
from tpu_autoscaler.actuators.gcp import (
    GcpApiError,
    GcpRest,
    TokenProvider,
    note_list_failure,
)
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.obs import maybe_span
from tpu_autoscaler.topology.catalog import SLICE_SHAPES

log = logging.getLogger(__name__)

_BASE = "https://tpu.googleapis.com/v2alpha1"

# Cloud TPU API state -> our ProvisionStatus state.
_STATE_MAP = {
    "CREATING": ACCEPTED,
    "ACCEPTED": ACCEPTED,
    "WAITING_FOR_RESOURCES": PROVISIONING,
    "PROVISIONING": PROVISIONING,
    "ACTIVE": ACTIVE,
    "FAILED": FAILED,
    "SUSPENDED": FAILED,
    "SUSPENDING": FAILED,
}

#: Consecutive complete LISTs an id must be absent from before its
#: absence is even worth confirming (one miss could be read-after-write
#: lag on a just-created resource).  Absence alone is never terminal:
#: it triggers a per-id GET, and only that GET's 404 kills.
LIST_MISS_THRESHOLD = 2


class QueuedResourceActuator:
    """Implements the Actuator protocol over Cloud TPU queuedResources."""

    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, project: str, zone: str, dry_run: bool = False,
                 rest: GcpRest | None = None,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "tpuas",
                 executor: ActuationExecutor | None = None,
                 batch_poll: bool = True):
        if not (project and zone):
            raise ValueError(
                "QueuedResource actuator needs --project and --location")
        self._parent = f"projects/{project}/locations/{zone}"
        self._rest = rest or GcpRest(
            dry_run=dry_run, token_provider=TokenProvider(),
            pool_maxsize=getattr(executor, "max_workers", None))
        self._runtime = runtime_version
        self._prefix = name_prefix
        self.executor = executor
        self._statuses: dict[str, ProvisionStatus] = {}
        self._done_at: dict[str, float] = {}
        # unit id -> owning queued-resource id.  For single-slice QRs the
        # unit IS the qr; a multislice QR owns node_count units named
        # "<qr>-<i>" (the API's nodeIdPrefix naming).
        self._unit_owner: dict[str, str] = {}
        self._qr_counts: dict[str, int] = {}
        self._ids = itertools.count(int(time.time()) % 100000)
        # qr ids whose create POST has succeeded — only these are
        # pollable (and only these may be declared deleted when a LIST
        # doesn't return them; a pending create is legitimately absent).
        self._created: set[str] = set()
        # Batched-LIST polling state: enabled until the endpoint proves
        # unavailable, then per-id GETs forever (the fallback).
        self._list_ok = batch_poll
        self._list_misses: dict[str, int] = {}
        # Dispatch guards (executor mode): at most one LIST in flight,
        # at most one GET per id in flight.
        self._poll_inflight = False
        self._gets_inflight: set[str] = set()
        self._tracer = None

    def set_metrics(self, metrics) -> None:
        """Wire the controller's metrics into the REST layer (the
        Controller calls this on construction) so rest_retries lands in
        the same registry as every other counter."""
        self._rest._metrics = metrics

    def set_tracer(self, tracer) -> None:
        """Wire the controller's tracer (obs/trace.py): serial creates
        and batched-LIST polls get spans; REST retries annotate them.
        Executor-mode dispatches are spanned by the executor itself."""
        self._tracer = tracer
        self._rest.tracer = tracer

    # ---- provision ------------------------------------------------------

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        if request.kind != "tpu-slice":
            raise ValueError(
                "QueuedResource actuator only provisions TPU slices; route "
                "cpu-node requests to the GKE actuator")
        shape = SLICE_SHAPES[request.shape_name]
        qr_id = (f"{self._prefix}-{request.shape_name}"
                 f"-{next(self._ids)}").replace(".", "-").lower()
        # The TPU API's acceleratorType uses product naming (TensorCore
        # counts on v4/v5p) — the catalog records that as product_name.
        accelerator = shape.product_name or shape.name
        node_spec: dict = {
            "parent": self._parent,
            "node": {
                "acceleratorType": accelerator,
                "runtimeVersion": self._runtime,
                "labels": {"autoscaler-tpu-dev-slice-id": qr_id},
            },
        }
        if request.count > 1:
            # ONE QueuedResource, N slices: Cloud TPU co-schedules the
            # slices of a multislice workload only when they are requested
            # together (multisliceParams; the XPK provisioning model —
            # SURVEY §6.8 / BASELINE config #4).  Node ids become
            # "<qr>-0".."<qr>-N-1" per the API's nodeIdPrefix naming.
            node_spec["multisliceParams"] = {
                "nodeCount": request.count,
                "nodeIdPrefix": qr_id,
            }
        else:
            node_spec["nodeId"] = qr_id
        body: dict = {"tpu": {"nodeSpec": [node_spec]}}
        if request.preemptible:
            body["spot"] = {}
        status = ProvisionStatus(id=qr_id, request=request, state=ACCEPTED)
        self._statuses[qr_id] = status
        self._qr_counts[qr_id] = request.count
        # The qr id itself always maps (cancel() deletes by provision id);
        # a multislice QR's member slices map to it too.
        self._unit_owner[qr_id] = qr_id
        for i in range(request.count if request.count > 1 else 0):
            self._unit_owner[f"{qr_id}-{i}"] = qr_id
        url = (f"{_BASE}/{self._parent}/queuedResources"
               f"?queuedResourceId={qr_id}")
        if self.executor is not None:
            self._rest.dispatch(
                self.executor, "POST", url, body,
                on_done=functools.partial(self._on_create_done, status),
                label=f"qr-create:{qr_id}")
            return status
        try:
            with maybe_span(self._tracer, "qr-create",
                            attrs={"qr": qr_id}):
                self._rest.post(url, body)
            self._created.add(qr_id)
        except Exception as e:  # noqa: BLE001 — surface as FAILED status
            self._rest.inc("actuator_api_errors")
            status.fail(e)
            log.exception("queued resource create failed for %s (%s)",
                          qr_id, status.reason)
        return status

    def _on_create_done(self, status: ProvisionStatus, result,
                        error) -> None:
        """Create-POST completion (reconcile thread, via drain)."""
        if error is not None:
            self._rest.inc("actuator_api_errors")
            if status.in_flight:  # a cancel() may have resolved it first
                status.fail(error)
                log.error("queued resource create failed for %s (%s): %s",
                          status.id, status.reason, error)
            return
        self._created.add(status.id)
        if not status.in_flight:
            # cancel() raced the create and its DELETE 404'd (the QR
            # did not exist yet).  It exists NOW, billed, with a
            # terminal status nothing will poll or reclaim — tear it
            # down (the GKE path's rollback queue, QR-style: a forced
            # delete supersedes any state).
            log.warning("queued resource %s created after its provision "
                        "was cancelled; deleting the orphan", status.id)
            self._delete_qr(status.id)

    # ---- delete / cancel ------------------------------------------------

    def delete(self, unit_id: str) -> None:
        qr_id = self._unit_owner.get(unit_id)
        if qr_id is None:
            # Unit ids from the controller come from k8s node labels;
            # queued-resource slices are standalone TPU VM fleets (no GKE
            # nodes), so a foreign id here means misconfiguration — say so
            # loudly instead of letting the DELETE 404 silently while the
            # billed slice keeps running (see class docstring: this
            # actuator is for QR-managed fleets where unit id == qr id).
            log.error("delete(%s): not a queued resource this actuator "
                      "provisioned; refusing blind delete", unit_id)
            return
        if self._qr_counts.get(qr_id, 1) > 1:
            # A multislice QR is one provisioning unit: deleting any
            # member slice tears down the whole QR (its siblings cannot
            # outlive it — by the time the controller reclaims, the
            # jobset spanning them is gone anyway).
            log.warning("delete(%s): multislice queued resource %s is "
                        "reclaimed whole (%d slices)", unit_id, qr_id,
                        self._qr_counts.get(qr_id, 1))
        self._delete_qr(qr_id)

    def _delete_qr(self, qr_id: str) -> None:
        try:
            # Deletes stay blocking in both modes: they are rare
            # (scale-down / cancel), and their bookkeeping must only
            # clear on confirmed success (docs/ACTUATION.md).  Traced
            # under the caller's context so a slice repair's whole-QR
            # delete lands in its slice_repair trace (docs/CHAOS.md).
            with maybe_span(self._tracer, "qr-delete",
                            attrs={"qr": qr_id}):
                self._rest.delete(
                    f"{_BASE}/{self._parent}/queuedResources/{qr_id}"
                    "?force=true")
            for uid, owner in list(self._unit_owner.items()):
                if owner == qr_id:
                    del self._unit_owner[uid]
            self._qr_counts.pop(qr_id, None)
        except Exception:  # noqa: BLE001 — retried by the maintain loop
            self._rest.inc("actuator_delete_errors")
            log.exception("queued resource delete failed for %s", qr_id)

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # force=true deletes a queued resource in any state, including
        # WAITING_FOR_RESOURCES (the classic stuck-queue case).
        self.delete(provision_id)
        status.state = FAILED
        status.error = "cancelled: provision timeout"

    # ---- poll -----------------------------------------------------------

    def poll(self, now: float) -> None:
        """Advance provisioning state.  Executor mode only *dispatches*
        I/O here; results land at the next pass's drain.  Serial mode
        applies them in-place (old blocking behavior)."""
        if not self._rest.dry_run:
            pollable = [qr_id for qr_id, s in self._statuses.items()
                        if s.state in (ACCEPTED, PROVISIONING)
                        and qr_id in self._created]
            if pollable and self._list_ok:
                # A serial LIST that proves unavailable flips _list_ok
                # and the SAME pass falls through to per-id GETs below
                # (executor mode learns at the next drain instead).
                self._poll_via_list()
            if pollable and not self._list_ok:
                self._poll_each(pollable)
        for qr_id, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(qr_id, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[qr_id]
                    self._done_at.pop(qr_id, None)
                    self._created.discard(qr_id)
                    self._list_misses.pop(qr_id, None)
                    if status.state == FAILED:
                        # A FAILED provision's units never registered:
                        # its ownership bookkeeping would otherwise leak
                        # forever under a chronic-stockout retry loop
                        # (fresh qr_id every pass).  ACTIVE units keep
                        # theirs until delete() reclaims them.
                        for uid, owner in list(self._unit_owner.items()):
                            if owner == qr_id:
                                del self._unit_owner[uid]
                        self._qr_counts.pop(qr_id, None)

    # -- batched LIST path

    def _poll_via_list(self) -> None:
        if self.executor is not None:
            if self._poll_inflight:
                return  # previous LIST still pending: no pile-up
            self._poll_inflight = True
            self.executor.submit(self._fetch_list_once,
                                 self._on_list_done, label="qr-list")
            return
        try:
            with maybe_span(self._tracer, "qr-list"):
                items = self._fetch_list_blocking()
        except Exception as e:  # noqa: BLE001 — transient; retry next pass
            self._rest.inc("actuator_poll_errors")
            self._note_list_failure(e)
            return
        self._apply_list(items)

    def _list_url(self, page_token: str) -> str:
        from urllib.parse import quote

        # The page token is opaque and may hold reserved characters; an
        # unencoded '+' would decode as a space server-side and the 400
        # would permanently flip polling to per-id GETs — on exactly the
        # large fleets where batching matters.
        return (f"{_BASE}/{self._parent}/queuedResources?pageSize=500"
                + (f"&pageToken={quote(page_token, safe='')}"
                   if page_token else ""))

    def _fetch_list_once(self) -> dict[str, dict]:
        """All queuedResources under the parent, keyed by short id.
        Runs on an executor worker: touches NO actuator state beyond
        immutable config.  A retryable failure mid-pagination restarts
        the whole LIST via the executor's reschedule."""
        return self._fetch_pages(lambda url: self._rest.once("GET", url))

    def _fetch_list_blocking(self) -> dict[str, dict]:
        return self._fetch_pages(self._rest.get)

    def _fetch_pages(self, fetch) -> dict[str, dict]:
        items: dict[str, dict] = {}
        page_token = ""
        while True:
            resp = fetch(self._list_url(page_token))
            for qr in resp.get("queuedResources", []):
                name = qr.get("name", "")
                items[name.rsplit("/", 1)[-1]] = qr
            page_token = resp.get("nextPageToken", "")
            if not page_token:
                return items

    def _on_list_done(self, items, error) -> None:
        """LIST completion (reconcile thread, via drain)."""
        self._poll_inflight = False
        if error is not None:
            self._rest.inc("actuator_poll_errors")
            self._note_list_failure(error)
            return
        self._apply_list(items)

    def _note_list_failure(self, error) -> None:
        if note_list_failure(self._rest, error, "queuedResources"):
            self._list_ok = False

    def _apply_list(self, items: dict[str, dict]) -> None:
        """Apply one complete LIST to every pollable status (reconcile
        thread).  Recomputes the pollable set at apply time — statuses
        may have changed since the LIST was dispatched."""
        batch = 0
        confirm: list[str] = []
        for qr_id, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING) \
                    or qr_id not in self._created:
                continue
            qr = items.get(qr_id)
            if qr is None:
                misses = self._list_misses.get(qr_id, 0) + 1
                self._list_misses[qr_id] = misses
                if misses >= LIST_MISS_THRESHOLD:
                    # Absence alone never kills — the LIST index can lag
                    # writes by several poll intervals.  Confirm with a
                    # per-id GET; only its 404 is terminal
                    # (deleted-out-of-band), a found QR applies state.
                    confirm.append(qr_id)
                continue
            self._list_misses.pop(qr_id, None)
            batch += 1
            self._apply_state(status, qr)
        self._rest.observe("poll_batch_size", batch)
        if confirm:
            self._poll_each(confirm)

    # -- per-id GET fallback

    def _poll_each(self, pollable: list[str]) -> None:
        for qr_id in pollable:
            url = f"{_BASE}/{self._parent}/queuedResources/{qr_id}"
            if self.executor is not None:
                if qr_id in self._gets_inflight:
                    continue
                self._gets_inflight.add(qr_id)
                self._rest.dispatch(
                    self.executor, "GET", url,
                    on_done=functools.partial(self._on_get_done, qr_id),
                    label=f"qr-poll:{qr_id}")
                continue
            try:
                with maybe_span(self._tracer, "qr-poll",
                                attrs={"qr": qr_id}):
                    qr = self._rest.get(url)
            except GcpApiError as e:
                if e.http_status == 404:
                    # Deleted out of band (operator, janitor, TTL): a
                    # 404 can never heal, so mark the provision FAILED
                    # — its demand re-provisions — instead of re-polling
                    # it forever as transient.
                    self._fail_deleted(self._statuses[qr_id])
                    continue
                self._rest.inc("actuator_poll_errors")
                log.exception("queued resource poll failed for %s", qr_id)
                continue
            except Exception:  # noqa: BLE001 — transient; retry next pass
                self._rest.inc("actuator_poll_errors")
                log.exception("queued resource poll failed for %s", qr_id)
                continue
            self._apply_state(self._statuses[qr_id], qr)

    def _on_get_done(self, qr_id: str, qr, error) -> None:
        """Per-id GET completion (reconcile thread, via drain)."""
        self._gets_inflight.discard(qr_id)
        status = self._statuses.get(qr_id)
        if status is None or status.state not in (ACCEPTED, PROVISIONING):
            return  # pruned or terminal (e.g. cancelled) while in flight
        if error is not None:
            if isinstance(error, GcpApiError) and error.http_status == 404:
                self._fail_deleted(status)
                return
            self._rest.inc("actuator_poll_errors")
            log.warning("queued resource poll failed for %s: %s",
                        qr_id, error)
            return
        self._apply_state(status, qr)

    # -- shared state application

    def _fail_deleted(self, status: ProvisionStatus) -> None:
        status.state = FAILED
        status.error = ("queued resource deleted out of band "
                        "(not found while polling)")
        status.reason = "deleted-out-of-band"
        self._list_misses.pop(status.id, None)
        log.warning("queued resource %s deleted out of band; marking "
                    "FAILED so its demand re-provisions", status.id)

    def _apply_state(self, status: ProvisionStatus, qr: dict) -> None:
        state_obj = qr.get("state") or {}
        api_state = state_obj.get("state", "")
        mapped = _STATE_MAP.get(api_state, PROVISIONING)
        if mapped == ACTIVE:
            status.state = mapped
            count = self._qr_counts.get(status.id, 1)
            status.unit_ids = (
                [status.id] if count == 1
                else [f"{status.id}-{i}" for i in range(count)])
        elif mapped == FAILED:
            # The API attaches the denial detail as a google.rpc
            # Status under the state's *Data field (failedData for
            # FAILED, suspendedData/suspendingData otherwise) —
            # that message is where stockout-vs-quota lives.
            detail = ""
            for key in ("failedData", "suspendedData",
                        "suspendingData"):
                err = (state_obj.get(key) or {}).get("error") or {}
                if err.get("message"):
                    detail = err["message"]
                    break
            status.fail(f"{api_state}: {detail}" if detail
                        else api_state)
        else:
            status.state = mapped

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())
