"""Cloud TPU QueuedResource actuator (L2 alternative).

Second real actuator, the XPK-style slice-provisioning path: instead of GKE
node pools, slices are requested from the Cloud TPU queued-resources API
(the capacity queue).  Its native lifecycle (ACCEPTED → PROVISIONING →
ACTIVE → FAILED/SUSPENDED) is exactly the ProvisionStatus state set — the
reference's "submit deployment, poll provisioning state" pattern
(deployments.py) maps 1:1, minus the one-in-flight restriction.

Reference-parity role: this is the secondary actuator the way
container_service.py (classic ACS) was secondary to engine_scaler.py.

Scope note: queued resources create *standalone TPU VM slices*, not GKE
nodes — use this actuator for QR-managed fleets where the supply-unit id IS
the queued-resource id, paired with the node-registration agent
(``tpu_autoscaler/agent.py``, ``deploy/agent-daemonset.yaml``) that stamps
SLICE_ID_LABEL with that id on each host's Node object.  For GKE clusters
use ``GkeNodePoolActuator``, whose node pools register labeled nodes
natively.
"""

from __future__ import annotations

import itertools
import logging
import time

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.actuators.gcp import GcpRest, TokenProvider
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.topology.catalog import SLICE_SHAPES

log = logging.getLogger(__name__)

_BASE = "https://tpu.googleapis.com/v2alpha1"

# Cloud TPU API state -> our ProvisionStatus state.
_STATE_MAP = {
    "CREATING": ACCEPTED,
    "ACCEPTED": ACCEPTED,
    "WAITING_FOR_RESOURCES": PROVISIONING,
    "PROVISIONING": PROVISIONING,
    "ACTIVE": ACTIVE,
    "FAILED": FAILED,
    "SUSPENDED": FAILED,
    "SUSPENDING": FAILED,
}


class QueuedResourceActuator:
    """Implements the Actuator protocol over Cloud TPU queuedResources."""

    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, project: str, zone: str, dry_run: bool = False,
                 rest: GcpRest | None = None,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "tpuas"):
        if not (project and zone):
            raise ValueError(
                "QueuedResource actuator needs --project and --location")
        self._parent = f"projects/{project}/locations/{zone}"
        self._rest = rest or GcpRest(dry_run=dry_run,
                                     token_provider=TokenProvider())
        self._runtime = runtime_version
        self._prefix = name_prefix
        self._statuses: dict[str, ProvisionStatus] = {}
        self._done_at: dict[str, float] = {}
        # unit id -> owning queued-resource id.  For single-slice QRs the
        # unit IS the qr; a multislice QR owns node_count units named
        # "<qr>-<i>" (the API's nodeIdPrefix naming).
        self._unit_owner: dict[str, str] = {}
        self._qr_counts: dict[str, int] = {}
        self._ids = itertools.count(int(time.time()) % 100000)

    def set_metrics(self, metrics) -> None:
        """Wire the controller's metrics into the REST layer (the
        Controller calls this on construction) so rest_retries lands in
        the same registry as every other counter."""
        self._rest._metrics = metrics

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        if request.kind != "tpu-slice":
            raise ValueError(
                "QueuedResource actuator only provisions TPU slices; route "
                "cpu-node requests to the GKE actuator")
        shape = SLICE_SHAPES[request.shape_name]
        qr_id = (f"{self._prefix}-{request.shape_name}"
                 f"-{next(self._ids)}").replace(".", "-").lower()
        # The TPU API's acceleratorType uses product naming (TensorCore
        # counts on v4/v5p) — the catalog records that as product_name.
        accelerator = shape.product_name or shape.name
        node_spec: dict = {
            "parent": self._parent,
            "node": {
                "acceleratorType": accelerator,
                "runtimeVersion": self._runtime,
                "labels": {"autoscaler-tpu-dev-slice-id": qr_id},
            },
        }
        if request.count > 1:
            # ONE QueuedResource, N slices: Cloud TPU co-schedules the
            # slices of a multislice workload only when they are requested
            # together (multisliceParams; the XPK provisioning model —
            # SURVEY §6.8 / BASELINE config #4).  Node ids become
            # "<qr>-0".."<qr>-N-1" per the API's nodeIdPrefix naming.
            node_spec["multisliceParams"] = {
                "nodeCount": request.count,
                "nodeIdPrefix": qr_id,
            }
        else:
            node_spec["nodeId"] = qr_id
        body: dict = {"tpu": {"nodeSpec": [node_spec]}}
        if request.preemptible:
            body["spot"] = {}
        status = ProvisionStatus(id=qr_id, request=request, state=ACCEPTED)
        self._statuses[qr_id] = status
        self._qr_counts[qr_id] = request.count
        # The qr id itself always maps (cancel() deletes by provision id);
        # a multislice QR's member slices map to it too.
        self._unit_owner[qr_id] = qr_id
        for i in range(request.count if request.count > 1 else 0):
            self._unit_owner[f"{qr_id}-{i}"] = qr_id
        try:
            self._rest.post(
                f"{_BASE}/{self._parent}/queuedResources"
                f"?queuedResourceId={qr_id}", body)
        except Exception as e:  # noqa: BLE001 — surface as FAILED status
            self._rest.inc("actuator_api_errors")
            status.fail(e)
            log.exception("queued resource create failed for %s (%s)",
                          qr_id, status.reason)
        return status

    def delete(self, unit_id: str) -> None:
        qr_id = self._unit_owner.get(unit_id)
        if qr_id is None:
            # Unit ids from the controller come from k8s node labels;
            # queued-resource slices are standalone TPU VM fleets (no GKE
            # nodes), so a foreign id here means misconfiguration — say so
            # loudly instead of letting the DELETE 404 silently while the
            # billed slice keeps running (see class docstring: this
            # actuator is for QR-managed fleets where unit id == qr id).
            log.error("delete(%s): not a queued resource this actuator "
                      "provisioned; refusing blind delete", unit_id)
            return
        if self._qr_counts.get(qr_id, 1) > 1:
            # A multislice QR is one provisioning unit: deleting any
            # member slice tears down the whole QR (its siblings cannot
            # outlive it — by the time the controller reclaims, the
            # jobset spanning them is gone anyway).
            log.warning("delete(%s): multislice queued resource %s is "
                        "reclaimed whole (%d slices)", unit_id, qr_id,
                        self._qr_counts.get(qr_id, 1))
        try:
            self._rest.delete(
                f"{_BASE}/{self._parent}/queuedResources/{qr_id}"
                "?force=true")
            for uid, owner in list(self._unit_owner.items()):
                if owner == qr_id:
                    del self._unit_owner[uid]
            self._qr_counts.pop(qr_id, None)
        except Exception:  # noqa: BLE001 — retried by the maintain loop
            self._rest.inc("actuator_delete_errors")
            log.exception("queued resource delete failed for %s", unit_id)

    def poll(self, now: float) -> None:
        for qr_id, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING):
                continue
            if self._rest.dry_run:
                continue
            try:
                qr = self._rest.get(
                    f"{_BASE}/{self._parent}/queuedResources/{qr_id}")
            except Exception:  # noqa: BLE001 — transient; retry next pass
                self._rest.inc("actuator_poll_errors")
                log.exception("queued resource poll failed for %s", qr_id)
                continue
            state_obj = qr.get("state") or {}
            api_state = state_obj.get("state", "")
            mapped = _STATE_MAP.get(api_state, PROVISIONING)
            if mapped == ACTIVE:
                status.state = mapped
                count = self._qr_counts.get(qr_id, 1)
                status.unit_ids = (
                    [qr_id] if count == 1
                    else [f"{qr_id}-{i}" for i in range(count)])
            elif mapped == FAILED:
                # The API attaches the denial detail as a google.rpc
                # Status under the state's *Data field (failedData for
                # FAILED, suspendedData/suspendingData otherwise) —
                # that message is where stockout-vs-quota lives.
                detail = ""
                for key in ("failedData", "suspendedData",
                            "suspendingData"):
                    err = (state_obj.get(key) or {}).get("error") or {}
                    if err.get("message"):
                        detail = err["message"]
                        break
                status.fail(f"{api_state}: {detail}" if detail
                            else api_state)
            else:
                status.state = mapped
        for qr_id, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(qr_id, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[qr_id]
                    self._done_at.pop(qr_id, None)

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # force=true deletes a queued resource in any state, including
        # WAITING_FOR_RESOURCES (the classic stuck-queue case).
        self.delete(provision_id)
        status.state = FAILED
        status.error = "cancelled: provision timeout"
