"""In-memory actuator: materializes Ready nodes into a FakeKube.

The reference's tests mocked the cloud entirely (mock.Mock() on the Azure
SDK — SURVEY.md §5); this fake goes further and *behaves* like the cloud:
provisions are asynchronous with a configurable delay and pass through the
QueuedResource-shaped states (ACCEPTED → PROVISIONING → ACTIVE), then real
node payloads appear in the fake apiserver with the full GKE TPU label
contract.  Multi-host slices can materialize their hosts gradually
(``stagger_seconds``) to exercise the all-hosts-Ready barrier.
"""

from __future__ import annotations

import itertools

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.k8s.payloads import cpu_node_payload, tpu_host_payload
from tpu_autoscaler.topology.catalog import cpu_shape_by_name, shape_by_name


class FakeActuator:
    """Implements the Actuator protocol against a FakeKube."""

    # Terminal (ACTIVE/FAILED) statuses are pruned after this long so a
    # long-running process doesn't accumulate them unboundedly.
    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, kube: FakeKube, *, provision_delay: float = 0.0,
                 stagger_seconds: float = 0.0, fail_shapes: set[str] = ()):
        self._kube = kube
        self._delay = provision_delay
        self._stagger = stagger_seconds
        self._fail_shapes = set(fail_shapes)
        self._statuses: dict[str, ProvisionStatus] = {}
        self._submitted_at: dict[str, float] = {}
        self._done_at: dict[str, float] = {}
        self._ids = itertools.count(1)
        self._now = 0.0
        self.deleted_units: list[str] = []

    # ---- Actuator protocol ---------------------------------------------

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        pid = f"prov-{next(self._ids)}"
        status = ProvisionStatus(id=pid, request=request, state=ACCEPTED)
        self._statuses[pid] = status
        self._submitted_at[pid] = self._now
        return status

    def delete(self, unit_id: str) -> None:
        self.deleted_units.append(unit_id)
        for payload in list(self._kube.list_nodes()):
            labels = payload.get("metadata", {}).get("labels", {})
            if labels.get("autoscaler.tpu.dev/slice-id") == unit_id:
                self._kube.delete_node(payload["metadata"]["name"])

    def poll(self, now: float) -> None:
        self._now = now
        for pid, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING):
                continue
            if status.request.shape_name in self._fail_shapes:
                status.state = FAILED
                status.error = "fake quota exhausted"
                continue
            elapsed = now - self._submitted_at[pid]
            if elapsed < self._delay:
                status.state = PROVISIONING
                continue
            self._materialize(pid, status, now)
        # Track terminal times and prune old terminal statuses.
        for pid, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(pid, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[pid]
                    self._submitted_at.pop(pid, None)
                    self._done_at.pop(pid, None)

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # Tear down any partially-materialized hosts (staggered slices).
        req = status.request
        if req.kind == "tpu-slice":
            if req.count == 1:
                self.delete(f"{req.shape_name}-{provision_id}")
            else:
                for i in range(req.count):
                    self.delete(f"{req.shape_name}-{provision_id}-s{i}")
        status.state = FAILED
        status.error = "cancelled: provision timeout"

    # ---- materialization ------------------------------------------------

    def _materialize(self, pid: str, status: ProvisionStatus,
                     now: float) -> None:
        req = status.request
        if req.kind == "tpu-slice":
            # count > 1 = one multislice provisioning unit (a single
            # QueuedResource with node_count=N): all member slices
            # materialize under one status, like Cloud TPU co-scheduling.
            shape = shape_by_name(req.shape_name)
            slice_ids = ([f"{req.shape_name}-{pid}"] if req.count == 1 else
                         [f"{req.shape_name}-{pid}-s{i}"
                          for i in range(req.count)])
            elapsed = now - self._submitted_at[pid] - self._delay
            hosts_up = (shape.hosts if self._stagger <= 0
                        else min(shape.hosts, 1 + int(elapsed / self._stagger)))
            for slice_id in slice_ids:
                for i in range(hosts_up):
                    name = f"{slice_id}-h{i}"
                    if not any(n["metadata"]["name"] == name
                               for n in self._kube.list_nodes()):
                        self._kube.add_node(tpu_host_payload(
                            shape, slice_id, i, created_at=now,
                            preemptible=req.preemptible))
            if hosts_up == shape.hosts:
                status.state = ACTIVE
                status.unit_ids = list(slice_ids)
            else:
                status.state = PROVISIONING
        else:
            shape = cpu_shape_by_name(req.shape_name)
            for i in range(req.count):
                unit_id = f"cpu-{pid}-{i}"
                self._kube.add_node(cpu_node_payload(
                    shape, unit_id, created_at=now))
            status.state = ACTIVE
            status.unit_ids = [f"cpu-{pid}-{i}" for i in range(req.count)]
