"""In-memory actuator: materializes Ready nodes into a FakeKube.

The reference's tests mocked the cloud entirely (mock.Mock() on the Azure
SDK — SURVEY.md §5); this fake goes further and *behaves* like the cloud:
provisions are asynchronous with a configurable delay and pass through the
QueuedResource-shaped states (ACCEPTED → PROVISIONING → ACTIVE), then real
node payloads appear in the fake apiserver with the full GKE TPU label
contract.  Multi-host slices can materialize their hosts gradually
(``stagger_seconds``) to exercise the all-hosts-Ready barrier.

Fault injection (ISSUE 7) is first-class and seedable, so the chaos
engine (``tpu_autoscaler/chaos``) and the repair/regression tests share
ONE fault model instead of ad-hoc subclasses:

- ``fail_prob``     — each provision is doomed with this probability
                      (drawn from the injected ``rng`` at submit time)
                      and FAILs on a later poll, like a quota rejection;
- ``fail_shapes``   — shapes that always FAIL (hard stockout);
- ``fail_window``   — a time window during which every in-flight
                      provision FAILs (zonal stockout; mid-provision
                      stockouts are provisions caught in flight by it);
- ``host_failure_prob`` — after a slice goes ACTIVE, one of its hosts
                      dies ``host_failure_delay`` seconds later
                      (``notready`` or ``delete``) — the partial-slice
                      failure the ICI-atomic repair path exists for;
- ``preempt_unit``  — stamps the impending-termination taint on a live
                      unit's hosts (spot reclamation notice).
"""

from __future__ import annotations

import itertools
import random

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.k8s.payloads import cpu_node_payload, tpu_host_payload
from tpu_autoscaler.topology.catalog import (
    SLICE_ID_LABEL,
    cpu_shape_by_name,
    shape_by_name,
)

#: Taint key the reconciler treats as an impending involuntary
#: termination (asserted against reconciler.TERMINATION_TAINT_KEYS at
#: use, so a rename there cannot silently de-claw chaos preemptions).
PREEMPT_TAINT = "cloud.google.com/impending-node-termination"


class FakeActuator:
    """Implements the Actuator protocol against a FakeKube."""

    # Terminal (ACTIVE/FAILED) statuses are pruned after this long so a
    # long-running process doesn't accumulate them unboundedly.
    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, kube: FakeKube, *, provision_delay: float = 0.0,
                 stagger_seconds: float = 0.0, fail_shapes: set[str] = (),
                 rng: random.Random | None = None, fail_prob: float = 0.0,
                 host_failure_prob: float = 0.0,
                 host_failure_delay: float = 30.0,
                 host_failure_mode: str = "notready"):
        self._kube = kube
        self._delay = provision_delay
        self._stagger = stagger_seconds
        self._fail_shapes = set(fail_shapes)
        self._rng = rng if rng is not None else random.Random(0)
        self._fail_prob = fail_prob
        self._host_failure_prob = host_failure_prob
        self._host_failure_delay = host_failure_delay
        if host_failure_mode not in ("notready", "delete"):
            raise ValueError(
                f"host_failure_mode must be 'notready' or 'delete', "
                f"got {host_failure_mode!r}")
        self._host_failure_mode = host_failure_mode
        self._statuses: dict[str, ProvisionStatus] = {}
        self._submitted_at: dict[str, float] = {}
        self._done_at: dict[str, float] = {}
        self._ids = itertools.count(1)
        self._now = 0.0
        self.deleted_units: list[str] = []
        # Fault state: provisions doomed at submit time (fail_prob /
        # fail_in_flight), the active stockout window, and host
        # failures scheduled for ACTIVE slices: (at, node, mode).
        self._doomed: dict[str, str] = {}
        self._fail_window: tuple[float, float, str] | None = None
        self._host_failures: list[tuple[float, str, str]] = []
        self.injected_host_failures: list[str] = []

    # ---- fault-injection knobs (ISSUE 7) --------------------------------

    def set_fail_window(self, start: float, end: float,
                        error: str = "chaos: zone out of capacity "
                                     "(stockout)") -> None:
        """Provisions in flight while ``start <= now < end`` FAIL with
        ``error`` — a zonal stockout window.  One window at a time."""
        self._fail_window = (start, end, error)

    def set_provision_delay(self, delay: float) -> None:
        """Change the provisioning delay mid-run (ISSUE 10 chaos
        latency-regression knob).  ``poll`` compares elapsed time
        against the CURRENT delay, so raising it stalls in-flight
        provisions too — and restoring it releases them on the next
        poll: a regression window's length is exactly the injected
        latency."""
        self._delay = delay

    def fail_in_flight(self, error: str = "chaos: provisioning aborted "
                                          "(out of capacity)") -> None:
        """Doom every currently in-flight provision (mid-provision
        stockout): each FAILs on its next poll."""
        for pid, status in self._statuses.items():
            if status.in_flight:
                self._doomed.setdefault(pid, error)

    def preempt_unit(self, unit_id: str) -> None:
        """Stamp the impending-termination taint on the unit's hosts —
        the spot-reclamation notice the drain path keys on."""
        from tpu_autoscaler.controller.reconciler import (
            TERMINATION_TAINT_KEYS,
        )

        assert PREEMPT_TAINT in TERMINATION_TAINT_KEYS, \
            "PREEMPT_TAINT drifted from the reconciler's taint set"
        for payload in self._kube.list_nodes():
            labels = payload.get("metadata", {}).get("labels", {})
            if labels.get(SLICE_ID_LABEL) == unit_id:
                self._kube.taint_node(payload["metadata"]["name"],
                                      PREEMPT_TAINT)

    def fail_host(self, node_name: str, mode: str = "notready") -> None:
        """Kill one host now: NotReady (kubelet gone) or delete (node
        object withdrawn) — the partial-slice failure primitive."""
        self.injected_host_failures.append(node_name)
        if mode == "delete":
            self._kube.delete_node(node_name)
        else:
            self._kube.set_node_ready(node_name, False)

    # ---- Actuator protocol ---------------------------------------------

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        pid = f"prov-{next(self._ids)}"
        status = ProvisionStatus(id=pid, request=request, state=ACCEPTED)
        self._statuses[pid] = status
        self._submitted_at[pid] = self._now
        if self._fail_prob > 0.0 and self._rng.random() < self._fail_prob:
            self._doomed[pid] = "chaos: injected quota failure"
        return status

    def delete(self, unit_id: str) -> None:
        self.deleted_units.append(unit_id)
        for payload in list(self._kube.list_nodes()):
            labels = payload.get("metadata", {}).get("labels", {})
            if labels.get(SLICE_ID_LABEL) == unit_id:
                self._kube.delete_node(payload["metadata"]["name"])

    def poll(self, now: float) -> None:
        self._now = now
        window = self._fail_window
        for pid, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING):
                continue
            doom = self._doomed.pop(pid, None)
            if doom is not None:
                status.fail(doom)
                continue
            if window is not None and window[0] <= now < window[1]:
                status.fail(window[2])
                continue
            if status.request.shape_name in self._fail_shapes:
                status.state = FAILED
                status.error = "fake quota exhausted"
                continue
            elapsed = now - self._submitted_at[pid]
            if elapsed < self._delay:
                status.state = PROVISIONING
                continue
            self._materialize(pid, status, now)
        # Scheduled host failures whose time came.
        due = [f for f in self._host_failures if f[0] <= now]
        if due:
            self._host_failures = [f for f in self._host_failures
                                   if f[0] > now]
            current = {n["metadata"]["name"] for n in self._kube.list_nodes()}
            for _at, node_name, mode in due:
                if node_name in current:
                    self.fail_host(node_name, mode)
        # Track terminal times and prune old terminal statuses.
        for pid, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(pid, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[pid]
                    self._submitted_at.pop(pid, None)
                    self._done_at.pop(pid, None)

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # Tear down any partially-materialized hosts (staggered slices).
        req = status.request
        if req.kind == "tpu-slice":
            if req.count == 1:
                self.delete(f"{req.shape_name}-{provision_id}")
            else:
                for i in range(req.count):
                    self.delete(f"{req.shape_name}-{provision_id}-s{i}")
        status.state = FAILED
        status.error = "cancelled: provision timeout"

    # ---- materialization ------------------------------------------------

    def _materialize(self, pid: str, status: ProvisionStatus,
                     now: float) -> None:
        req = status.request
        if req.kind == "tpu-slice":
            # count > 1 = one multislice provisioning unit (a single
            # QueuedResource with node_count=N): all member slices
            # materialize under one status, like Cloud TPU co-scheduling.
            shape = shape_by_name(req.shape_name)
            slice_ids = ([f"{req.shape_name}-{pid}"] if req.count == 1 else
                         [f"{req.shape_name}-{pid}-s{i}"
                          for i in range(req.count)])
            elapsed = now - self._submitted_at[pid] - self._delay
            hosts_up = (shape.hosts if self._stagger <= 0
                        else min(shape.hosts, 1 + int(elapsed / self._stagger)))
            for slice_id in slice_ids:
                for i in range(hosts_up):
                    name = f"{slice_id}-h{i}"
                    if not any(n["metadata"]["name"] == name
                               for n in self._kube.list_nodes()):
                        self._kube.add_node(tpu_host_payload(
                            shape, slice_id, i, created_at=now,
                            preemptible=req.preemptible))
            if hosts_up == shape.hosts:
                status.state = ACTIVE
                status.unit_ids = list(slice_ids)
                if (self._host_failure_prob > 0.0 and shape.hosts > 1
                        and self._rng.random() < self._host_failure_prob):
                    # One host of one member slice dies later: the
                    # partial-slice failure the repair path must turn
                    # into a whole-slice replacement, never a backfill.
                    victim_slice = self._rng.choice(slice_ids)
                    host = self._rng.randrange(shape.hosts)
                    self._host_failures.append((
                        now + self._host_failure_delay,
                        f"{victim_slice}-h{host}",
                        self._host_failure_mode))
            else:
                status.state = PROVISIONING
        else:
            shape = cpu_shape_by_name(req.shape_name)
            for i in range(req.count):
                unit_id = f"cpu-{pid}-{i}"
                self._kube.add_node(cpu_node_payload(
                    shape, unit_id, created_at=now))
            status.state = ACTIVE
            status.unit_ids = [f"cpu-{pid}-{i}" for i in range(req.count)]
