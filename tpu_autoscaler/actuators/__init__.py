"""Scaling actuators (L2/L1): the cloud-facing side of the autoscaler.

Analog of the reference's scaler.py §Scaler / engine_scaler.py
§EngineScaler / container_service.py, with the ARM-template machinery
replaced by a narrow provision/delete interface over atomic supply units
(TPU slices; CPU nodes are 1-node slices).  Implementations:

- ``fake.FakeActuator`` — in-memory, materializes Ready nodes into the fake
  apiserver; powers e2e loop tests (SURVEY.md §5 "Implication").
- ``gke.GkeNodePoolActuator`` — GKE node-pool create/delete over REST.
- ``queued_resources.QueuedResourceActuator`` — Cloud TPU QueuedResources.
"""

from tpu_autoscaler.actuators.base import Actuator, ProvisionStatus

__all__ = ["Actuator", "ProvisionStatus"]
