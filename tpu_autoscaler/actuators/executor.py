"""Bounded-concurrency actuation executor: the pipelined dispatch layer.

Why this exists (ISSUE 3): the planner was built so disjoint gangs
provision in parallel (engine/planner.py module docstring), yet every
actuation HTTP round-trip used to run serially on the reconcile thread
— ``Reconciler._scale`` submitted provisions one blocking POST at a
time, each actuator ``poll()`` GET'd in-flight provisions one by one,
and ``GcpRest._request`` slept its retry backoffs in-place.  A pass
over a busy fleet cost O(in-flight + new requests) RTTs of wall-clock;
this executor makes it ~1 RTT.

Threading / consistency model (docs/ACTUATION.md):

- ``submit()`` hands a thunk (one HTTP attempt, typically
  ``GcpRest.once``) to a capped ``ThreadPoolExecutor``.  The thunk runs
  off-thread and must not touch actuator or executor state — it only
  returns a value or raises.
- ALL executor bookkeeping (``_running``, ``_parked``) and every
  ``on_done`` completion callback run on the reconcile thread, inside
  ``drain()`` — called at the top of ``reconcile_once``.  Actuator
  state is therefore mutated only on the reconcile thread, and the
  class needs no locks at all: the TAT2xx thread-discipline checker
  stays clean with zero waivers by construction, not by annotation.
- A thunk that must back off raises :class:`RetryLater` instead of
  sleeping.  ``drain()`` parks the call and re-dispatches it once its
  ``retry_at`` arrives — the reconcile thread never sleeps, and a
  backing-off call never occupies a worker slot.
- Retries are deadline-aware: a reschedule that would land past the
  call's deadline (or past ``max_attempts``) delivers the terminal
  error to ``on_done`` instead.

Metrics: ``actuation_dispatch_latency_seconds`` (submit → result
delivered), ``actuation_pool_depth`` (outstanding calls after each
drain), ``actuation_retries_rescheduled``, ``actuation_callback_errors``.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import heapq
import itertools
import logging
import random
import time
from typing import Any, Callable

from tpu_autoscaler import concurrency
from tpu_autoscaler.backoff import (
    REST_BACKOFF_BASE_S,
    REST_BACKOFF_CAP_S,
    REST_RETRY_AFTER_CAP_FACTOR,
    backoff_seconds,
)

log = logging.getLogger(__name__)

DEFAULT_MAX_WORKERS = 16
DEFAULT_MAX_ATTEMPTS = 5
#: Default per-call deadline: submit → final delivery, including parked
#: backoff time.  Generous (a provision POST is idempotent-keyed and
#: the controller's provision_timeout backstops it anyway).
DEFAULT_DEADLINE_S = 120.0


class RetryLater(Exception):
    """Raised by a dispatched thunk to mean "transient — try the same
    call again after a backoff".  The executor reschedules the call at
    ``retry_at`` instead of anyone sleeping.

    ``retry_after``: optional server hint (Retry-After) in seconds.
    ``attempt_free``: the retry neither burns a backoff attempt nor
    waits (401 token re-resolution — mirrors the blocking loop, which
    re-authes immediately exactly once; a second attempt-free failure
    goes terminal).
    ``terminal()``: the exception delivered to ``on_done`` when retries
    are exhausted or the deadline passes (subclasses refine it —
    ``gcp.GcpRetryable`` turns itself back into a ``GcpApiError``).
    """

    def __init__(self, cause: str, retry_after: Any = None,
                 attempt_free: bool = False) -> None:
        super().__init__(cause)
        self.cause = cause
        self.retry_after = retry_after
        self.attempt_free = attempt_free

    def terminal(self) -> Exception:
        return self.__cause__ if self.__cause__ is not None else self


@dataclasses.dataclass(eq=False)  # identity semantics for list removal
class _Call:
    fn: Callable[[], Any]
    on_done: Callable[[Any, Exception | None], None]
    label: str
    submitted_at: float
    deadline_at: float
    attempt: int = 0
    free_retries_used: int = 0
    future: concurrent.futures.Future | None = None
    # Trace span for this call (obs/trace.py), opened at submit() on
    # the reconcile thread — capturing the submitting context is how a
    # trace crosses the pool boundary: the worker thunk never touches
    # the tracer, and the span is ended at drain time on the reconcile
    # thread again (docs/OBSERVABILITY.md).
    span: Any = None


class ActuationExecutor:
    """Capped-concurrency dispatcher for actuator HTTP calls.

    ``submit()`` and ``drain()`` must be called from the reconcile
    thread only (see module docstring).  ``clock`` is injectable for
    the deadline/reschedule tests; it must be monotonic-like.
    """

    def __init__(self, max_workers: int = DEFAULT_MAX_WORKERS,
                 metrics: Any = None,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 deadline_s: float = DEFAULT_DEADLINE_S,
                 backoff_base_s: float = REST_BACKOFF_BASE_S,
                 backoff_cap_s: float = REST_BACKOFF_CAP_S,
                 rng: random.Random | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.max_workers = max_workers
        self._pool = concurrency.pool_executor(
            max_workers, thread_name_prefix="actuation")
        self._metrics = metrics
        self._max_attempts = max_attempts
        self._deadline_s = deadline_s
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._rng = rng or random.Random()
        self._clock = clock
        self._tracer: Any = None
        self._running: list[_Call] = []
        # Parked retries: (retry_at, seq, call) min-heap.
        self._parked: list[tuple[float, int, _Call]] = []
        self._seq = itertools.count()

    # -- wiring ----------------------------------------------------------

    def set_metrics(self, metrics: Any) -> None:
        """Wire the controller's metrics registry (the Controller calls
        this on construction, like Actuator.set_metrics)."""
        self._metrics = metrics

    def set_tracer(self, tracer: Any) -> None:
        """Wire the controller's tracer: every dispatched call gets a
        span from submit() to drain-time delivery, parented under
        whatever span was current at submit time (the provision's
        ``dispatch`` span).  None (the default) costs nothing."""
        self._tracer = tracer

    def _inc(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.inc(name)

    def _observe(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.observe(name, value)

    # -- reconcile-thread API --------------------------------------------

    @property
    def depth(self) -> int:
        """Outstanding calls: dispatched + parked for retry."""
        return len(self._running) + len(self._parked)

    def submit(self, fn: Callable[[], Any],
               on_done: Callable[[Any, Exception | None], None], *,
               label: str = "", deadline_s: float | None = None) -> None:
        """Dispatch ``fn`` to the pool; ``on_done(result, error)`` fires
        on the reconcile thread during a later ``drain()``."""
        now = self._clock()
        call = _Call(fn=fn, on_done=on_done, label=label, submitted_at=now,
                     deadline_at=now + (deadline_s if deadline_s is not None
                                        else self._deadline_s))
        if self._tracer is not None:
            call.span = self._tracer.start(
                f"actuate:{label or 'call'}")
        self._dispatch(call)

    def _dispatch(self, call: _Call) -> None:
        call.future = self._pool.submit(call.fn)
        self._running.append(call)

    def drain(self) -> int:
        """Deliver completed calls and wake due retries; returns the
        number of completions delivered.  The ONLY place on_done runs."""
        now = self._clock()
        while self._parked and self._parked[0][0] <= now:
            _, _, call = heapq.heappop(self._parked)
            call.attempt += 1
            self._dispatch(call)
        completed = [c for c in self._running
                     if c.future is not None and c.future.done()]
        # Rebuild _running BEFORE running callbacks: an on_done that
        # submits new work must land in the live list, not be lost to a
        # post-loop reassignment.
        self._running = [c for c in self._running if c not in completed]
        delivered = 0
        for call in completed:
            delivered += self._finish(call, now)
        if self._metrics is not None:
            self._metrics.set_gauge("actuation_pool_depth", self.depth)
        return delivered

    def _finish(self, call: _Call, now: float) -> int:
        """One completed future: park a retry, or deliver the result."""
        exc = call.future.exception()
        if isinstance(exc, RetryLater) and exc.attempt_free:
            if call.free_retries_used < 1:
                # 401-style re-resolution: immediate redispatch, no
                # attempt burned, no backoff — blocking-loop parity.
                call.free_retries_used += 1
                self._dispatch(call)
                return 0
            exc = exc.terminal()  # second auth failure is terminal
        if isinstance(exc, RetryLater):
            delay = backoff_seconds(
                call.attempt, exc.retry_after,
                base_s=self._backoff_base_s, cap_s=self._backoff_cap_s,
                retry_after_cap_s=(self._backoff_cap_s
                                   * REST_RETRY_AFTER_CAP_FACTOR),
                rng=self._rng)
            retry_at = now + delay
            if (call.attempt + 1 < self._max_attempts
                    and retry_at <= call.deadline_at):
                heapq.heappush(self._parked,
                               (retry_at, next(self._seq), call))
                self._inc("actuation_retries_rescheduled")
                if self._tracer is not None and call.span is not None:
                    self._tracer.event(
                        call.span, "rescheduled",
                        {"attempt": call.attempt + 1,
                         "delay_s": round(delay, 3), "cause": exc.cause},
                        t=call.span.start + (now - call.submitted_at))
                log.debug("actuation call %s rescheduled in %.2fs "
                          "(attempt %d/%d): %s", call.label, delay,
                          call.attempt + 1, self._max_attempts, exc.cause)
                return 0
            exc = exc.terminal()
        self._observe("actuation_dispatch_latency_seconds",
                      now - call.submitted_at)
        if self._tracer is not None and call.span is not None:
            # Anchor at the tracer's clock, duration from the
            # executor's clock: the span duration equals the
            # actuation_dispatch_latency_seconds observation exactly,
            # under real AND injected/simulated clocks.
            attrs: dict[str, Any] = {"attempts": call.attempt + 1}
            if exc is not None:
                attrs["error"] = str(exc)
            self._tracer.end(
                call.span,
                t=call.span.start + (now - call.submitted_at),
                attrs=attrs)
        try:
            if exc is None:
                call.on_done(call.future.result(), None)
            else:
                call.on_done(None, exc)
        except Exception:  # noqa: BLE001 — one callback must not starve
            # the rest of the drain (crash-only degradation point).
            if self._metrics is not None:
                self._metrics.inc("actuation_callback_errors")
            log.exception("actuation completion callback failed (%s)",
                          call.label)
        return 1

    # -- tests / bench / shutdown only -----------------------------------

    def wait(self, timeout: float | None = 10.0) -> None:
        """Block until every currently-dispatched future completes.
        Parked retries are NOT waited on (they need a drain to wake).
        Tests and bench only — the control loop never blocks here."""
        concurrent.futures.wait(
            [c.future for c in self._running if c.future is not None],
            timeout=timeout)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
