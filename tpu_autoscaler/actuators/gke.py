"""GKE node-pool actuator: the primary real-cluster actuator (L2).

TPU-native analog of the reference's engine_scaler.py §EngineScaler: where
the reference bumped ARM-template `<pool>Count`/`<pool>Offset` parameters
and redeployed, this creates/deletes whole GKE node pools — one node pool
per supply unit (one TPU slice, or one CPU node), which is GKE's own
semantics for multi-host TPU slices (a multi-host TPU node pool IS one
slice).  Scale-down therefore deletes exactly one unit's hardware without
touching poolmates — the same invariant as the reference's
`delete_resources_for_node` (template_processing.py), achieved by
construction instead of template surgery.

Unlike deployments.py's one-deployment-in-flight serialization, disjoint
node-pool operations run in parallel; idempotence comes from the planner's
gang tagging (see engine/planner.py docstring).

Actuation pipeline (ISSUE 3, docs/ACTUATION.md): with an
``ActuationExecutor`` attached, pool-create POSTs (including a CPU
request's N per-node pools) and rollback deletes dispatch concurrently,
and polling batches into ONE operations LIST under the cluster's
project/location instead of one GET per create operation (per-op GET
remains the fallback when LIST is unavailable).  All actuator state
mutates on the reconcile thread via drain-run callbacks.
"""

from __future__ import annotations

import functools
import itertools
import logging
import time

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.actuators.executor import ActuationExecutor
from tpu_autoscaler.actuators.gcp import (
    GcpRest,
    TokenProvider,
    note_list_failure,
)
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.obs import maybe_span
from tpu_autoscaler.topology.catalog import (
    POOL_LABEL,
    SLICE_ID_LABEL,
    SLICE_SHAPES,
    cpu_shape_by_name,
)

log = logging.getLogger(__name__)

_BASE = "https://container.googleapis.com/v1"


class GkeNodePoolActuator:
    """Implements the Actuator protocol over the GKE node-pools API."""

    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, project: str, location: str, cluster: str,
                 dry_run: bool = False, rest: GcpRest | None = None,
                 pool_prefix: str = "tpuas",
                 api_base: str = _BASE,
                 executor: ActuationExecutor | None = None,
                 batch_poll: bool = True):
        if not (project and location and cluster):
            raise ValueError(
                "GKE actuator needs --project, --location and --cluster")
        self._api_base = api_base
        self._parent = (f"projects/{project}/locations/{location}"
                        f"/clusters/{cluster}")
        # Operations live under the project/location, not the cluster.
        self._ops_parent = f"projects/{project}/locations/{location}"
        self._rest = rest or GcpRest(
            dry_run=dry_run, token_provider=TokenProvider(),
            pool_maxsize=getattr(executor, "max_workers", None))
        self._prefix = pool_prefix
        self.executor = executor
        self._statuses: dict[str, ProvisionStatus] = {}
        self._operations: dict[str, list[str]] = {}  # provision id -> ops
        self._pools: dict[str, list[str]] = {}       # provision id -> pools
        self._done_at: dict[str, float] = {}
        # Pools created by a provision() that then failed mid-loop, still
        # awaiting rollback delete (retried from poll(): GKE rejects
        # mutations on a pool whose create operation is in progress, so
        # an immediate delete would itself fail in exactly the partial-
        # failure scenarios rollback exists for).
        self._rollbacks: dict[str, list[str]] = {}
        self._rollback_attempts: dict[str, int] = {}
        self._ids = itertools.count(int(time.time()) % 100000)
        # Executor-mode bookkeeping (all mutated on the reconcile thread
        # via drain-run callbacks):
        self._pending_creates: dict[str, int] = {}   # pid -> outstanding POSTs
        self._created_pools: dict[str, list[str]] = {}
        self._rollback_inflight: set[str] = set()    # pool names
        self._list_ok = batch_poll
        self._poll_inflight = False
        self._op_gets_inflight: set[str] = set()     # op names
        self._tracer = None

    def set_metrics(self, metrics) -> None:
        """Wire the controller's metrics into the REST layer (the
        Controller calls this on construction) so rest_retries lands in
        the same registry as every other counter."""
        self._rest._metrics = metrics

    def set_tracer(self, tracer) -> None:
        """Wire the controller's tracer (obs/trace.py): serial creates
        and batched operations-LIST polls get spans; REST retries
        annotate them.  Executor-mode dispatches are spanned by the
        executor itself."""
        self._tracer = tracer
        self._rest.tracer = tracer

    ROLLBACK_MAX_ATTEMPTS = 40

    # ---- request -> GKE node pool spec ---------------------------------

    def _pool_body(self, request: ProvisionRequest, pool_name: str) -> dict:
        if request.kind == "tpu-slice":
            shape = SLICE_SHAPES[request.shape_name]
            config: dict = {
                "machineType": shape.machine_type,
                "labels": {SLICE_ID_LABEL: pool_name,
                           POOL_LABEL: self._prefix},
            }
            if request.preemptible:
                config["spot"] = True
            body = {
                "nodePool": {
                    "name": pool_name,
                    "initialNodeCount": shape.hosts,
                    "config": config,
                }
            }
            if shape.multi_host:
                # Multi-host TPU pools need the slice placement policy so
                # GKE provisions one ICI-connected slice.
                body["nodePool"]["placementPolicy"] = {
                    "type": "COMPACT",
                    "tpuTopology": shape.topology_label,
                }
            return body
        # CPU unit: ONE single-node pool per unit, so each CPU node stays an
        # independent drain/delete unit (pool name == unit id == slice-id
        # label).  A count-N pool would collapse N nodes into one unit.
        shape_cpu = cpu_shape_by_name(request.shape_name)
        return {
            "nodePool": {
                "name": pool_name,
                "initialNodeCount": 1,
                "config": {
                    "machineType": shape_cpu.machine_type,
                    "labels": {SLICE_ID_LABEL: pool_name,
                               POOL_LABEL: self._prefix},
                },
            }
        }

    # ---- Actuator protocol ---------------------------------------------

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        count = request.count if request.kind == "cpu-node" else 1
        pool_names = [
            (f"{self._prefix}-{request.shape_name}"
             f"-{next(self._ids)}").replace(".", "-").lower()
            for _ in range(count)
        ]
        status = ProvisionStatus(id=pool_names[0], request=request,
                                 state=ACCEPTED)
        self._statuses[status.id] = status
        self._pools[status.id] = pool_names
        if self.executor is not None:
            # All pool creates for this request dispatch concurrently;
            # success/failure (and the rollback set for a partial
            # failure) resolve in _on_create_done at a later drain.
            self._operations[status.id] = []
            self._pending_creates[status.id] = len(pool_names)
            for pool_name in pool_names:
                self._rest.dispatch(
                    self.executor, "POST",
                    f"{self._api_base}/{self._parent}/nodePools",
                    self._pool_body(request, pool_name),
                    on_done=functools.partial(self._on_create_done,
                                              status, pool_name),
                    label=f"pool-create:{pool_name}")
            return status
        ops: list[str] = []
        created: list[str] = []
        try:
            for pool_name in pool_names:
                with maybe_span(self._tracer, "pool-create",
                                attrs={"pool": pool_name}):
                    op = self._rest.post(
                        f"{self._api_base}/{self._parent}/nodePools",
                        self._pool_body(request, pool_name))
                created.append(pool_name)
                if op.get("name"):
                    ops.append(op["name"])
        except Exception as e:  # noqa: BLE001 — surface as FAILED status
            self._rest.inc("actuator_api_errors")
            status.fail(e)
            log.exception("node pool create failed for %s (%s)",
                          status.id, status.reason)
            # Queue rollback of pools already created in this request: a
            # FAILED status is terminal (cancel() only covers in-flight
            # states), so without this the partial pools would register
            # nodes that only idle-timeout reclaims — billed, unused
            # capacity — while the retry provisions a fresh full set.
            # Deletion happens from poll(), retried until GKE accepts it
            # (the create operation must finish first).
            if created:
                self._rollbacks[status.id] = list(created)
        self._operations[status.id] = ops
        return status

    def _on_create_done(self, status: ProvisionStatus, pool_name: str,
                        result, error) -> None:
        """One pool-create POST resolved (reconcile thread, via drain)."""
        pid = status.id
        remaining = self._pending_creates.get(pid, 1) - 1
        self._pending_creates[pid] = remaining
        if error is not None:
            self._rest.inc("actuator_api_errors")
            if status.state != FAILED:
                status.fail(error)
                log.error("node pool create failed for %s (%s): %s",
                          pid, status.reason, error)
        else:
            self._created_pools.setdefault(pid, []).append(pool_name)
            if result.get("name"):
                self._operations.setdefault(pid, []).append(result["name"])
        if remaining > 0:
            return
        # Last POST of the request resolved: settle the outcome.
        self._pending_creates.pop(pid, None)
        created = self._created_pools.pop(pid, [])
        if status.state == FAILED and created:
            # Partial failure: roll back the siblings that DID create
            # (same contract as the serial path).
            self._rollbacks[pid] = created

    def _process_rollbacks(self) -> None:
        """Retry deletes of partially-created pools until GKE accepts
        them (or attempts run out and idle timeout becomes the backstop).
        Executor mode dispatches the deletes concurrently, guarded so a
        pool with a delete already in flight is never double-dispatched
        (poll runs every pass; completions land at drain time)."""
        for pid, pools in list(self._rollbacks.items()):
            pending = [p for p in pools if p in self._rollback_inflight]
            to_try = [p for p in pools if p not in self._rollback_inflight]
            if not to_try:
                continue  # whole set already dispatched, awaiting drain
            attempts = self._rollback_attempts.get(pid, 0) + 1
            self._rollback_attempts[pid] = attempts
            if attempts > self.ROLLBACK_MAX_ATTEMPTS:
                log.error(
                    "giving up rollback for %s after %d attempts; pools %s "
                    "will be reclaimed by idle timeout", pid, attempts - 1,
                    pools)
                self._rollbacks.pop(pid, None)
                self._rollback_attempts.pop(pid, None)
                continue
            if self.executor is not None:
                for pool_name in to_try:
                    self._rollback_inflight.add(pool_name)
                    self._rest.dispatch(
                        self.executor, "DELETE",
                        f"{self._api_base}/{self._parent}"
                        f"/nodePools/{pool_name}",
                        on_done=functools.partial(self._on_rollback_done,
                                                  pid, pool_name),
                        label=f"pool-rollback:{pool_name}")
                continue
            remaining: list[str] = list(pending)
            for pool_name in to_try:
                try:
                    self._rest.delete(
                        f"{self._api_base}/{self._parent}"
                        f"/nodePools/{pool_name}")
                except Exception:  # noqa: BLE001 — create op still running
                    self._rest.inc("rollback_retries")
                    log.debug("rollback delete not yet accepted for %s",
                              pool_name, exc_info=True)
                    remaining.append(pool_name)
            if not remaining:
                self._rollbacks.pop(pid, None)
                self._rollback_attempts.pop(pid, None)
            else:
                self._rollbacks[pid] = remaining

    def _on_rollback_done(self, pid: str, pool_name: str, result,
                          error) -> None:
        """Rollback DELETE resolved (reconcile thread, via drain)."""
        self._rollback_inflight.discard(pool_name)
        if error is not None:
            # Create op likely still running; poll() redispatches until
            # ROLLBACK_MAX_ATTEMPTS runs out.
            self._rest.inc("rollback_retries")
            log.debug("rollback delete not yet accepted for %s: %s",
                      pool_name, error)
            return
        remaining = [p for p in self._rollbacks.get(pid, [])
                     if p != pool_name]
        if remaining:
            self._rollbacks[pid] = remaining
        else:
            self._rollbacks.pop(pid, None)
            self._rollback_attempts.pop(pid, None)

    def delete(self, unit_id: str) -> None:
        try:
            # Blocking in both modes (rare, scale-down path; see
            # docs/ACTUATION.md).  The span parents under the caller's
            # context — a slice repair's whole-unit delete shows up in
            # its slice_repair trace (docs/CHAOS.md).
            with maybe_span(self._tracer, "pool-delete",
                            attrs={"unit": unit_id}):
                self._rest.delete(
                    f"{self._api_base}/{self._parent}/nodePools/{unit_id}")
        except Exception:  # noqa: BLE001 — retried by the maintain loop
            self._rest.inc("actuator_delete_errors")
            log.exception("node pool delete failed for %s", unit_id)

    # ---- poll -----------------------------------------------------------

    def poll(self, now: float) -> None:
        self._process_rollbacks()
        if not self._rest.dry_run and self._pending_ops():
            if self._list_ok:
                # A serial LIST that proves unavailable flips _list_ok
                # and the SAME pass falls through to per-op GETs below
                # (executor mode learns at the next drain instead).
                self._poll_ops_via_list()
            if not self._list_ok:
                self._poll_ops_each()
        # Statuses with no operations recorded (dry-run returns no op
        # names; creates may still be dispatching) stay/advance here.
        for pid, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING):
                continue
            if not (self._operations.get(pid) or []) \
                    and not self._rest.dry_run \
                    and pid not in self._pending_creates:
                status.state = PROVISIONING
        for pid, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(pid, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[pid]
                    self._operations.pop(pid, None)
                    self._pools.pop(pid, None)
                    self._done_at.pop(pid, None)

    def _pending_ops(self) -> dict[str, list[str]]:
        """provision id -> operation names still being waited on.
        Excludes provisions with create POSTs still outstanding: a
        multi-pool request must never resolve ACTIVE off the ops that
        DID land while a sibling's create is parked on a retry."""
        return {pid: ops for pid, status in self._statuses.items()
                if status.state in (ACCEPTED, PROVISIONING)
                and pid not in self._pending_creates
                and (ops := self._operations.get(pid))}

    # -- batched operations LIST

    def _poll_ops_via_list(self) -> None:
        if self.executor is not None:
            if self._poll_inflight:
                return
            self._poll_inflight = True
            self.executor.submit(self._fetch_ops_once,
                                 self._on_ops_list_done, label="gke-ops")
            return
        try:
            with maybe_span(self._tracer, "gke-ops-list"):
                ops_map = self._fetch_ops(self._rest.get)
        except Exception as e:  # noqa: BLE001 — transient; retry next pass
            self._rest.inc("actuator_poll_errors")
            self._note_list_failure(e)
            return
        self._apply_ops(ops_map)

    def _fetch_ops_once(self) -> dict[str, dict]:
        """Worker-thread LIST: no actuator state beyond immutable config."""
        return self._fetch_ops(lambda url: self._rest.once("GET", url))

    def _fetch_ops(self, fetch) -> dict[str, dict]:
        """ONE operations LIST under the project/location, indexed by
        both the full operation name and its last path segment (create
        responses record fully-qualified names; the list returns
        whatever the API surface uses)."""
        resp = fetch(f"{self._api_base}/{self._ops_parent}/operations")
        ops_map: dict[str, dict] = {}
        for op in resp.get("operations", []):
            name = op.get("name", "")
            if not name:
                continue
            ops_map[name] = op
            ops_map[name.rsplit("/", 1)[-1]] = op
        return ops_map

    def _on_ops_list_done(self, ops_map, error) -> None:
        self._poll_inflight = False
        if error is not None:
            self._rest.inc("actuator_poll_errors")
            self._note_list_failure(error)
            return
        self._apply_ops(ops_map)

    def _note_list_failure(self, error) -> None:
        if note_list_failure(self._rest, error, "GKE operations"):
            self._list_ok = False

    def _apply_ops(self, ops_map: dict[str, dict]) -> None:
        """Resolve every waiting provision against one ops snapshot
        (reconcile thread).  An operation absent from the snapshot is
        treated as still running — the controller's provision_timeout
        backstops an operation that truly vanished."""
        batch = 0
        for pid, ops in self._pending_ops().items():
            status = self._statuses[pid]
            all_done, error = True, None
            for op_name in ops:
                op = ops_map.get(op_name) \
                    or ops_map.get(op_name.rsplit("/", 1)[-1])
                if op is None:
                    all_done = False
                    self._rest.inc("poll_ops_missing")
                    continue
                batch += 1
                if op.get("status") != "DONE":
                    all_done = False
                    continue
                if op.get("error"):
                    error = str(op["error"])
            self._resolve(status, pid, all_done, error)
        self._rest.observe("poll_batch_size", batch)

    # -- per-operation GET fallback

    def _poll_ops_each(self) -> None:
        for pid, ops in self._pending_ops().items():
            if self.executor is not None:
                for op_name in ops:
                    if op_name in self._op_gets_inflight:
                        continue
                    self._op_gets_inflight.add(op_name)
                    # Operation names are already fully qualified
                    # (projects/.../operations/...).
                    self._rest.dispatch(
                        self.executor, "GET",
                        f"{self._api_base}/{op_name}",
                        on_done=functools.partial(self._on_op_get_done,
                                                  pid, op_name),
                        label=f"op-poll:{op_name.rsplit('/', 1)[-1]}")
                continue
            status = self._statuses[pid]
            all_done, error = True, None
            for op_name in ops:
                try:
                    op = self._rest.get(f"{self._api_base}/{op_name}")
                except Exception:  # noqa: BLE001 — transient; retry later
                    self._rest.inc("actuator_poll_errors")
                    log.exception("operation poll failed for %s", pid)
                    all_done = False
                    break
                if op.get("status") != "DONE":
                    all_done = False
                    break
                if op.get("error"):
                    error = str(op["error"])
            self._resolve(status, pid, all_done, error)

    def _on_op_get_done(self, pid: str, op_name: str, op, error) -> None:
        """Per-op GET resolved (reconcile thread, via drain).  Completed
        ops are dropped from the provision's waiting list; when the list
        empties the provision resolves."""
        self._op_gets_inflight.discard(op_name)
        status = self._statuses.get(pid)
        if status is None or status.state not in (ACCEPTED, PROVISIONING):
            return
        if error is not None:
            self._rest.inc("actuator_poll_errors")
            log.warning("operation poll failed for %s: %s", pid, error)
            return
        if op.get("status") != "DONE":
            return
        if op.get("error"):
            status.fail(str(op["error"]))
            return
        remaining = [o for o in self._operations.get(pid, [])
                     if o != op_name]
        self._operations[pid] = remaining
        if not remaining:
            self._resolve(status, pid, True, None)

    # -- shared resolution

    def _resolve(self, status: ProvisionStatus, pid: str, all_done: bool,
                 error: str | None) -> None:
        if error is not None:
            status.fail(error)
        elif all_done:
            status.state = ACTIVE
            status.unit_ids = list(self._pools.get(pid, [pid]))
        else:
            status.state = PROVISIONING

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # Delete whatever pools the stuck provision created; node-pool
        # deletion supersedes a pending create on GKE.  Marking FAILED
        # first also parks any still-in-flight create/poll dispatches:
        # their drain-time callbacks skip non-in-flight statuses.
        for pool_name in self._pools.get(provision_id, [provision_id]):
            self.delete(pool_name)
        status.state = FAILED
        status.error = "cancelled: provision timeout"
