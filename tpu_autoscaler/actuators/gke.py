"""GKE node-pool actuator: the primary real-cluster actuator (L2).

TPU-native analog of the reference's engine_scaler.py §EngineScaler: where
the reference bumped ARM-template `<pool>Count`/`<pool>Offset` parameters
and redeployed, this creates/deletes whole GKE node pools — one node pool
per supply unit (one TPU slice, or one CPU node), which is GKE's own
semantics for multi-host TPU slices (a multi-host TPU node pool IS one
slice).  Scale-down therefore deletes exactly one unit's hardware without
touching poolmates — the same invariant as the reference's
`delete_resources_for_node` (template_processing.py), achieved by
construction instead of template surgery.

Unlike deployments.py's one-deployment-in-flight serialization, disjoint
node-pool operations run in parallel; idempotence comes from the planner's
gang tagging (see engine/planner.py docstring).
"""

from __future__ import annotations

import itertools
import logging
import time

from tpu_autoscaler.actuators.base import (
    ACCEPTED,
    ACTIVE,
    FAILED,
    PROVISIONING,
    ProvisionStatus,
)
from tpu_autoscaler.actuators.gcp import GcpRest, TokenProvider
from tpu_autoscaler.engine.planner import ProvisionRequest
from tpu_autoscaler.topology.catalog import (
    POOL_LABEL,
    SLICE_ID_LABEL,
    SLICE_SHAPES,
    cpu_shape_by_name,
)

log = logging.getLogger(__name__)

_BASE = "https://container.googleapis.com/v1"


class GkeNodePoolActuator:
    """Implements the Actuator protocol over the GKE node-pools API."""

    STATUS_RETENTION_SECONDS = 900.0

    def __init__(self, project: str, location: str, cluster: str,
                 dry_run: bool = False, rest: GcpRest | None = None,
                 pool_prefix: str = "tpuas",
                 api_base: str = _BASE):
        if not (project and location and cluster):
            raise ValueError(
                "GKE actuator needs --project, --location and --cluster")
        self._api_base = api_base
        self._parent = (f"projects/{project}/locations/{location}"
                        f"/clusters/{cluster}")
        self._rest = rest or GcpRest(dry_run=dry_run,
                                     token_provider=TokenProvider())
        self._prefix = pool_prefix
        self._statuses: dict[str, ProvisionStatus] = {}
        self._operations: dict[str, list[str]] = {}  # provision id -> ops
        self._pools: dict[str, list[str]] = {}       # provision id -> pools
        self._done_at: dict[str, float] = {}
        # Pools created by a provision() that then failed mid-loop, still
        # awaiting rollback delete (retried from poll(): GKE rejects
        # mutations on a pool whose create operation is in progress, so
        # an immediate delete would itself fail in exactly the partial-
        # failure scenarios rollback exists for).
        self._rollbacks: dict[str, list[str]] = {}
        self._rollback_attempts: dict[str, int] = {}
        self._ids = itertools.count(int(time.time()) % 100000)

    def set_metrics(self, metrics) -> None:
        """Wire the controller's metrics into the REST layer (the
        Controller calls this on construction) so rest_retries lands in
        the same registry as every other counter."""
        self._rest._metrics = metrics

    ROLLBACK_MAX_ATTEMPTS = 40

    # ---- request -> GKE node pool spec ---------------------------------

    def _pool_body(self, request: ProvisionRequest, pool_name: str) -> dict:
        if request.kind == "tpu-slice":
            shape = SLICE_SHAPES[request.shape_name]
            config: dict = {
                "machineType": shape.machine_type,
                "labels": {SLICE_ID_LABEL: pool_name,
                           POOL_LABEL: self._prefix},
            }
            if request.preemptible:
                config["spot"] = True
            body = {
                "nodePool": {
                    "name": pool_name,
                    "initialNodeCount": shape.hosts,
                    "config": config,
                }
            }
            if shape.multi_host:
                # Multi-host TPU pools need the slice placement policy so
                # GKE provisions one ICI-connected slice.
                body["nodePool"]["placementPolicy"] = {
                    "type": "COMPACT",
                    "tpuTopology": shape.topology_label,
                }
            return body
        # CPU unit: ONE single-node pool per unit, so each CPU node stays an
        # independent drain/delete unit (pool name == unit id == slice-id
        # label).  A count-N pool would collapse N nodes into one unit.
        shape_cpu = cpu_shape_by_name(request.shape_name)
        return {
            "nodePool": {
                "name": pool_name,
                "initialNodeCount": 1,
                "config": {
                    "machineType": shape_cpu.machine_type,
                    "labels": {SLICE_ID_LABEL: pool_name,
                               POOL_LABEL: self._prefix},
                },
            }
        }

    # ---- Actuator protocol ---------------------------------------------

    def provision(self, request: ProvisionRequest) -> ProvisionStatus:
        count = request.count if request.kind == "cpu-node" else 1
        pool_names = [
            (f"{self._prefix}-{request.shape_name}"
             f"-{next(self._ids)}").replace(".", "-").lower()
            for _ in range(count)
        ]
        status = ProvisionStatus(id=pool_names[0], request=request,
                                 state=ACCEPTED)
        self._statuses[status.id] = status
        self._pools[status.id] = pool_names
        ops: list[str] = []
        created: list[str] = []
        try:
            for pool_name in pool_names:
                op = self._rest.post(f"{self._api_base}/{self._parent}/nodePools",
                                     self._pool_body(request, pool_name))
                created.append(pool_name)
                if op.get("name"):
                    ops.append(op["name"])
        except Exception as e:  # noqa: BLE001 — surface as FAILED status
            self._rest.inc("actuator_api_errors")
            status.fail(e)
            log.exception("node pool create failed for %s (%s)",
                          status.id, status.reason)
            # Queue rollback of pools already created in this request: a
            # FAILED status is terminal (cancel() only covers in-flight
            # states), so without this the partial pools would register
            # nodes that only idle-timeout reclaims — billed, unused
            # capacity — while the retry provisions a fresh full set.
            # Deletion happens from poll(), retried until GKE accepts it
            # (the create operation must finish first).
            if created:
                self._rollbacks[status.id] = list(created)
        self._operations[status.id] = ops
        return status

    def _process_rollbacks(self) -> None:
        """Retry deletes of partially-created pools until GKE accepts
        them (or attempts run out and idle timeout becomes the backstop)."""
        for pid, pools in list(self._rollbacks.items()):
            attempts = self._rollback_attempts.get(pid, 0) + 1
            self._rollback_attempts[pid] = attempts
            remaining: list[str] = []
            for pool_name in pools:
                try:
                    self._rest.delete(
                        f"{self._api_base}/{self._parent}"
                        f"/nodePools/{pool_name}")
                except Exception:  # noqa: BLE001 — create op still running
                    self._rest.inc("rollback_retries")
                    log.debug("rollback delete not yet accepted for %s",
                              pool_name, exc_info=True)
                    remaining.append(pool_name)
            if not remaining:
                self._rollbacks.pop(pid, None)
                self._rollback_attempts.pop(pid, None)
            elif attempts >= self.ROLLBACK_MAX_ATTEMPTS:
                log.error(
                    "giving up rollback for %s after %d attempts; pools %s "
                    "will be reclaimed by idle timeout", pid, attempts,
                    remaining)
                self._rollbacks.pop(pid, None)
            else:
                self._rollbacks[pid] = remaining

    def delete(self, unit_id: str) -> None:
        try:
            self._rest.delete(f"{self._api_base}/{self._parent}/nodePools/{unit_id}")
        except Exception:  # noqa: BLE001 — retried by the maintain loop
            self._rest.inc("actuator_delete_errors")
            log.exception("node pool delete failed for %s", unit_id)

    def poll(self, now: float) -> None:
        self._process_rollbacks()
        for pid, status in self._statuses.items():
            if status.state not in (ACCEPTED, PROVISIONING):
                continue
            ops = self._operations.get(pid) or []
            if not ops:
                if not self._rest.dry_run:
                    status.state = PROVISIONING
                continue
            all_done, error = True, None
            for op_name in ops:
                try:
                    # Operation names are already fully qualified
                    # (projects/.../operations/...).
                    op = self._rest.get(f"{self._api_base}/{op_name}")
                except Exception:  # noqa: BLE001 — transient; retry later
                    self._rest.inc("actuator_poll_errors")
                    log.exception("operation poll failed for %s", pid)
                    all_done = False
                    break
                if op.get("status") != "DONE":
                    all_done = False
                    break
                if op.get("error"):
                    error = str(op["error"])
            if error is not None:
                status.fail(error)
            elif all_done:
                status.state = ACTIVE
                status.unit_ids = list(self._pools.get(pid, [pid]))
            else:
                status.state = PROVISIONING
        for pid, status in list(self._statuses.items()):
            if status.state in (ACTIVE, FAILED):
                done = self._done_at.setdefault(pid, now)
                if now - done > self.STATUS_RETENTION_SECONDS:
                    del self._statuses[pid]
                    self._operations.pop(pid, None)
                    self._pools.pop(pid, None)
                    self._done_at.pop(pid, None)

    def statuses(self) -> list[ProvisionStatus]:
        return list(self._statuses.values())

    def cancel(self, provision_id: str) -> None:
        status = self._statuses.get(provision_id)
        if status is None or not status.in_flight:
            return
        # Delete whatever pools the stuck provision created; node-pool
        # deletion supersedes a pending create on GKE.
        for pool_name in self._pools.get(provision_id, [provision_id]):
            self.delete(pool_name)
        status.state = FAILED
        status.error = "cancelled: provision timeout"
