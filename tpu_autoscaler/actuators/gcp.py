"""Shared GCP REST plumbing for the real actuators (L1).

Analog of the reference's deployments.py (ARM submit/poll) — the thin,
serializable cloud-API layer under the scalers.  Deliberately SDK-free:
``requests`` + a bearer token cover the three verbs we need (POST create,
GET poll, DELETE teardown).  Token resolution order:

1. ``GCP_ACCESS_TOKEN`` env (operator-provided, e.g. `gcloud auth
   print-access-token`),
2. GCE/GKE metadata server (workload identity / attached SA) — the
   in-cluster path, mirroring how the reference used in-cluster service
   credentials.

Tokens are cached until ~5 minutes before expiry.

Every verb retries transient failures (429 / 5xx / connection errors)
with bounded exponential backoff + full jitter, honoring Retry-After —
the reference's deployments.py tolerated flaky ARM polls the same way;
without this a single 503 surfaced as a whole reconcile-pass exception.
A 401 mid-flight invalidates the cached token and re-resolves once
(metadata-server tokens rotate under us in-cluster).
"""

from __future__ import annotations

import logging
import os
import random
import time

log = logging.getLogger(__name__)

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


class GcpAuthError(RuntimeError):
    pass


class GcpApiError(RuntimeError):
    """A non-retryable (or retries-exhausted) googleapis error with the
    error envelope parsed out — requests' raise_for_status() loses the
    body, which is exactly where quota-vs-stockout-vs-permission lives
    (errors.classify_provision_error consumes this)."""

    def __init__(self, http_status: int, url: str, body: dict | str):
        self.http_status = http_status
        err = body.get("error", {}) if isinstance(body, dict) else {}
        self.status = err.get("status", "")            # e.g. RESOURCE_EXHAUSTED
        self.message = err.get("message", "") or (
            body if isinstance(body, str) else "")
        self.reasons = [e.get("reason", "") for e in err.get("errors", [])]
        detail = self.message or self.status or "(no error body)"
        super().__init__(
            f"HTTP {http_status} {self.status or ''} {detail} [{url}]"
            .replace("  ", " "))


class TokenProvider:
    def __init__(self):
        self._token: str | None = None
        self._expires_at = 0.0
        self._env_token_used: str | None = None

    def invalidate(self) -> None:
        """Drop the cached token so the next token() re-resolves — the
        401 recovery path: a metadata-server token can be revoked/rotated
        before its advertised expiry, and a stale env token adopted on
        metadata failure would otherwise 401 forever."""
        self._token = None
        self._expires_at = 0.0

    def token(self) -> str:
        if self._token and time.time() < self._expires_at - 300:
            return self._token
        env = os.environ.get("GCP_ACCESS_TOKEN")
        if env and env != self._env_token_used:
            # A fresh operator-provided token (gcloud tokens live <=1h);
            # once it ages out we do NOT silently re-adopt the same stale
            # value — we fall through to the metadata server instead.
            self._env_token_used = env
            self._token, self._expires_at = env, time.time() + 3000
            return env
        try:
            import requests

            r = requests.get(_METADATA_TOKEN_URL,
                             headers={"Metadata-Flavor": "Google"},
                             timeout=5)
            r.raise_for_status()
            data = r.json()
            self._token = data["access_token"]
            self._expires_at = time.time() + float(
                data.get("expires_in", 3600))
            return self._token
        except Exception as e:  # noqa: BLE001
            if env:
                # No metadata server but the operator gave us a token:
                # keep using it (it may be long-lived), but say so.
                log.warning("GCP_ACCESS_TOKEN is older than its assumed "
                            "lifetime and no metadata server is available; "
                            "continuing with the possibly-stale token")
                self._token, self._expires_at = env, time.time() + 3000
                return env
            raise GcpAuthError(
                "no GCP credentials: set GCP_ACCESS_TOKEN or run with a "
                "metadata server (GKE workload identity)") from e


#: HTTP statuses worth retrying: rate limits and server-side hiccups.
_RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


class GcpRest:
    """Minimal authenticated JSON REST client with dry-run support and
    transient-failure retries (see module docstring).

    ``metrics``: optional Metrics sink — each retried attempt increments
    ``rest_retries`` so operators can see a flaky control plane before
    it becomes an outage.  ``sleep``/``rng`` are injectable for tests.
    """

    max_attempts = 5
    backoff_base_s = 0.5
    backoff_cap_s = 8.0

    def __init__(self, dry_run: bool = False,
                 token_provider: TokenProvider | None = None,
                 metrics=None, sleep=time.sleep,
                 rng: random.Random | None = None):
        self.dry_run = dry_run
        self._tokens = token_provider or TokenProvider()
        self._metrics = metrics
        self._sleep = sleep
        self._rng = rng or random.Random()

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self._tokens.token()}",
                "Content-Type": "application/json"}

    def _backoff_seconds(self, attempt: int, retry_after) -> float:
        from tpu_autoscaler.backoff import backoff_seconds

        return backoff_seconds(
            attempt, retry_after, base_s=self.backoff_base_s,
            cap_s=self.backoff_cap_s,
            retry_after_cap_s=self.backoff_cap_s * 4, rng=self._rng)

    def inc(self, name: str) -> None:
        """Increment a counter on the wired metrics sink (no-op until
        the Controller calls the actuator's set_metrics)."""
        if self._metrics is not None:
            self._metrics.inc(name)

    def _note_retry(self, why: str, url: str, attempt: int) -> None:
        self.inc("rest_retries")
        log.warning("GCP REST %s (attempt %d/%d) %s — retrying",
                    why, attempt + 1, self.max_attempts, url)

    def _request(self, method: str, url: str, body: dict | None) -> dict:
        import requests

        reauthed = False
        attempt = 0
        while True:
            try:
                r = requests.request(
                    method, url, headers=self._headers(),
                    json=body if method == "POST" else None, timeout=30)
            except requests.exceptions.RequestException as e:
                if attempt + 1 >= self.max_attempts:
                    raise
                self._note_retry(f"connection error ({e.__class__.__name__})",
                                 url, attempt)
                self._sleep(self._backoff_seconds(attempt, None))
                attempt += 1
                continue
            if r.status_code == 401 and not reauthed:
                # Token revoked/rotated under us: re-resolve once, and
                # don't burn a backoff attempt on it.
                reauthed = True
                self._tokens.invalidate()
                self._note_retry("401 (re-resolving token)", url, attempt)
                continue
            if r.status_code in _RETRYABLE_STATUSES \
                    and attempt + 1 < self.max_attempts:
                self._note_retry(f"{r.status_code}", url, attempt)
                self._sleep(self._backoff_seconds(
                    attempt, r.headers.get("Retry-After")))
                attempt += 1
                continue
            if r.status_code >= 400:
                try:
                    body = r.json()
                except ValueError:
                    body = (r.text or "")[:500]
                raise GcpApiError(r.status_code, url, body)
            return r.json() if r.content else {}

    def get(self, url: str) -> dict:
        return self._request("GET", url, None)

    def post(self, url: str, body: dict) -> dict:
        if self.dry_run:
            log.info("[dry-run] POST %s %s", url, body)
            return {}
        return self._request("POST", url, body)

    def delete(self, url: str) -> dict:
        if self.dry_run:
            log.info("[dry-run] DELETE %s", url)
            return {}
        return self._request("DELETE", url, None)
