"""Shared GCP REST plumbing for the real actuators (L1).

Analog of the reference's deployments.py (ARM submit/poll) — the thin,
serializable cloud-API layer under the scalers.  Deliberately SDK-free:
``requests`` + a bearer token cover the three verbs we need (POST create,
GET poll, DELETE teardown).  Token resolution order:

1. ``GCP_ACCESS_TOKEN`` env (operator-provided, e.g. `gcloud auth
   print-access-token`),
2. GCE/GKE metadata server (workload identity / attached SA) — the
   in-cluster path, mirroring how the reference used in-cluster service
   credentials.

Tokens are cached until ~5 minutes before expiry; the cache is
lock-guarded and the refresh single-flighted, because the actuation
executor calls ``token()`` from concurrent worker threads.

Transport is a pooled ``requests.Session`` (connection/TLS reuse across
calls AND across worker threads — urllib3's pool is thread-safe) with
split connect/read timeouts, shared with the token provider's metadata
fetches.  Tests inject a ``transport`` callable instead.

Two dispatch modes share one attempt implementation (``once``):

- ``_request`` — the blocking loop: retries transient failures
  (429 / 5xx / connection errors) with bounded exponential backoff +
  full jitter, honoring Retry-After, sleeping in-place.  A 401
  mid-flight invalidates the cached token and re-resolves once.
- ``dispatch`` — the pipelined path: hands ONE attempt to the
  :class:`~tpu_autoscaler.actuators.executor.ActuationExecutor`; a
  retryable outcome raises :class:`GcpRetryable` (a ``RetryLater``) and
  the executor reschedules it at ``retry_at`` instead of sleeping.
"""

from __future__ import annotations

import functools
import logging
import os
import random
import threading
import time

from tpu_autoscaler import concurrency
from tpu_autoscaler.actuators.executor import ActuationExecutor, RetryLater
from tpu_autoscaler.backoff import (
    REST_BACKOFF_BASE_S,
    REST_BACKOFF_CAP_S,
    REST_RETRY_AFTER_CAP_FACTOR,
)

log = logging.getLogger(__name__)

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

#: Split timeouts: connect failures (dead VIP, blackholed route) surface
#: in seconds while a slow-but-alive response keeps the full read window.
CONNECT_TIMEOUT_S = 5.0
READ_TIMEOUT_S = 30.0
METADATA_TIMEOUT_S = (2.0, 5.0)

#: Connection-pool floor for the shared Session — matches the actuation
#: executor's default worker cap so concurrent dispatches never queue on
#: a pool slot below the concurrency cap (an actuator built with a
#: bigger executor passes its worker count through ``pool_maxsize``).
SESSION_POOL_MAXSIZE = 16

#: HTTP statuses on a batched-LIST endpoint meaning "this surface does
#: not support (or permit) listing" — shared by both actuators' poll
#: fallback machines so they can never drift on the classification.
LIST_UNAVAILABLE_STATUSES = frozenset({400, 403, 404, 501})


def list_unavailable(error) -> bool:
    """True when a batched-LIST failure means the endpoint is
    unavailable (flip to per-id polling for good) rather than a
    transient hiccup (keep LIST mode, retry next pass)."""
    return (isinstance(error, GcpApiError)
            and error.http_status in LIST_UNAVAILABLE_STATUSES)


def note_list_failure(rest, error, what: str) -> bool:
    """Shared batched-LIST failure handling for both actuators (they
    must never drift on this): counts the fallback, logs, and returns
    True when the caller should flip to per-id polling for good."""
    if list_unavailable(error):
        rest.inc("poll_list_fallbacks")
        log.warning("%s LIST unavailable (HTTP %d); falling back to "
                    "per-id polling", what, error.http_status)
        return True
    log.warning("%s batched poll failed: %s", what, error)
    return False


class GcpAuthError(RuntimeError):
    pass


class GcpApiError(RuntimeError):
    """A non-retryable (or retries-exhausted) googleapis error with the
    error envelope parsed out — requests' raise_for_status() loses the
    body, which is exactly where quota-vs-stockout-vs-permission lives
    (errors.classify_provision_error consumes this)."""

    def __init__(self, http_status: int, url: str, body: dict | str):
        self.http_status = http_status
        err = body.get("error", {}) if isinstance(body, dict) else {}
        self.status = err.get("status", "")            # e.g. RESOURCE_EXHAUSTED
        self.message = err.get("message", "") or (
            body if isinstance(body, str) else "")
        self.reasons = [e.get("reason", "") for e in err.get("errors", [])]
        detail = self.message or self.status or "(no error body)"
        super().__init__(
            f"HTTP {http_status} {self.status or ''} {detail} [{url}]"
            .replace("  ", " "))


class GcpRetryable(RetryLater):
    """A retryable HTTP outcome (429/5xx/connection error, or a 401
    pending token re-resolution) surfaced as data instead of an
    in-place sleep.  Carries enough to reconstruct the terminal error
    when retries run out."""

    def __init__(self, cause: str, retry_after=None,
                 http_status: int | None = None,
                 err_body: dict | str | None = None, url: str = "",
                 attempt_free: bool = False):
        super().__init__(cause, retry_after, attempt_free=attempt_free)
        self.http_status = http_status
        self.err_body = err_body
        self.url = url

    def terminal(self) -> Exception:
        if self.http_status is not None:
            return GcpApiError(self.http_status, self.url,
                               self.err_body if self.err_body is not None
                               else "")
        return self.__cause__ if self.__cause__ is not None else self


def _parse_error_body(r) -> dict | str:
    """The googleapis error envelope (or truncated text) from an error
    response.  A local helper — NOT a variable named ``body`` — so the
    request payload can never be shadowed/clobbered on an error path."""
    try:
        return r.json()
    except ValueError:
        return (r.text or "")[:500]


class TokenProvider:
    """Cached bearer-token resolution, thread-safe: the actuation
    executor's workers all call ``token()`` concurrently, so the cache
    is lock-guarded and a refresh is single-flight — one metadata-server
    fetch per expiry, not a stampede (waiters block on the lock and
    then read the fresh cache)."""

    def __init__(self, http=None):
        self._lock = concurrency.Lock()
        self._token: str | None = None
        self._expires_at = 0.0
        self._env_token_used: str | None = None
        # Metadata-fetch callable (requests.get-shaped).  GcpRest
        # attaches its pooled session's .get here so token refreshes
        # reuse the same connection pool.
        self._http = http

    def attach_http(self, http) -> None:
        """Adopt a transport for metadata fetches (first one wins — an
        explicitly injected ``http`` is never overridden)."""
        with self._lock:
            if self._http is None:
                self._http = http

    def invalidate(self) -> None:
        """Drop the cached token so the next token() re-resolves — the
        401 recovery path: a metadata-server token can be revoked/rotated
        before its advertised expiry, and a stale env token adopted on
        metadata failure would otherwise 401 forever."""
        with self._lock:
            self._token = None
            self._expires_at = 0.0

    def token(self) -> str:
        with self._lock:
            if self._token and time.time() < self._expires_at - 300:
                return self._token
            env = os.environ.get("GCP_ACCESS_TOKEN")
            if env and env != self._env_token_used:
                # A fresh operator-provided token (gcloud tokens live
                # <=1h); once it ages out we do NOT silently re-adopt the
                # same stale value — we fall through to the metadata
                # server instead.
                self._env_token_used = env
                self._token, self._expires_at = env, time.time() + 3000
                return env
            try:
                import requests

                http = self._http if self._http is not None \
                    else requests.get
                r = http(_METADATA_TOKEN_URL,  # analysis: allow=TAB801 single-flight refresh BY DESIGN (class docstring): waiters queue on the lock for the one bounded (METADATA_TIMEOUT_S) fetch instead of stampeding the metadata server
                         headers={"Metadata-Flavor": "Google"},
                         timeout=METADATA_TIMEOUT_S)
                r.raise_for_status()
                data = r.json()
                self._token = data["access_token"]
                self._expires_at = time.time() + float(
                    data.get("expires_in", 3600))
                return self._token
            except Exception as e:  # noqa: BLE001
                if env:
                    # No metadata server but the operator gave us a
                    # token: keep using it (it may be long-lived), but
                    # say so.
                    log.warning(
                        "GCP_ACCESS_TOKEN is older than its assumed "
                        "lifetime and no metadata server is available; "
                        "continuing with the possibly-stale token")
                    self._token, self._expires_at = env, time.time() + 3000
                    return env
                raise GcpAuthError(
                    "no GCP credentials: set GCP_ACCESS_TOKEN or run with "
                    "a metadata server (GKE workload identity)") from e


#: HTTP statuses worth retrying: rate limits and server-side hiccups.
_RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


class GcpRest:
    """Minimal authenticated JSON REST client with dry-run support and
    transient-failure retries (see module docstring).

    ``metrics``: optional Metrics sink — each retried attempt increments
    ``rest_retries`` so operators can see a flaky control plane before
    it becomes an outage.  ``sleep``/``rng``/``transport`` are
    injectable for tests; the default transport is a pooled Session.
    """

    max_attempts = 5
    backoff_base_s = REST_BACKOFF_BASE_S
    backoff_cap_s = REST_BACKOFF_CAP_S

    def __init__(self, dry_run: bool = False,
                 token_provider: TokenProvider | None = None,
                 metrics=None, sleep=time.sleep,
                 rng: random.Random | None = None,
                 transport=None, pool_maxsize: int | None = None):
        self.dry_run = dry_run
        self._tokens = token_provider or TokenProvider()
        self._metrics = metrics
        # Optional tracer (obs/trace.py): retries annotate whatever span
        # is current in the calling context (the serial dispatch span);
        # on executor workers the context is deliberately empty, so the
        # same code is a no-op there (the executor's own span carries
        # the attempt count instead).
        self.tracer = None
        self._sleep = sleep
        self._rng = rng or random.Random()
        if transport is None:
            import requests

            session = requests.Session()
            adapter = requests.adapters.HTTPAdapter(
                pool_connections=4,
                pool_maxsize=max(pool_maxsize or 0, SESSION_POOL_MAXSIZE))
            session.mount("https://", adapter)
            session.mount("http://", adapter)
            transport = session.request
            # Token refreshes ride the same connection pool.
            self._tokens.attach_http(session.get)
        self._transport = transport

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self._tokens.token()}",
                "Content-Type": "application/json"}

    def _backoff_seconds(self, attempt: int, retry_after) -> float:
        from tpu_autoscaler.backoff import backoff_seconds

        return backoff_seconds(
            attempt, retry_after, base_s=self.backoff_base_s,
            cap_s=self.backoff_cap_s,
            retry_after_cap_s=(self.backoff_cap_s
                               * REST_RETRY_AFTER_CAP_FACTOR),
            rng=self._rng)

    def inc(self, name: str) -> None:
        """Increment a counter on the wired metrics sink (no-op until
        the Controller calls the actuator's set_metrics)."""
        if self._metrics is not None:
            self._metrics.inc(name)

    def observe(self, name: str, value: float) -> None:
        """Record a summary observation on the wired metrics sink."""
        if self._metrics is not None:
            self._metrics.observe(name, value)

    def _note_retry(self, why: str, url: str, attempt: int) -> None:
        self.inc("rest_retries")
        if self.tracer is not None:
            self.tracer.event_current(
                "rest_retry", {"why": why, "attempt": attempt + 1})
        log.warning("GCP REST %s (attempt %d/%d) %s — retrying",
                    why, attempt + 1, self.max_attempts, url)

    # -- one attempt (shared by both dispatch modes) ---------------------

    def once(self, method: str, url: str, body: dict | None = None) -> dict:
        """ONE HTTP attempt.  Returns parsed JSON on success; raises
        :class:`GcpRetryable` for outcomes the caller may retry
        (429/5xx/connection errors, and a 401 after invalidating the
        cached token) and :class:`GcpApiError` for terminal ones.
        Never sleeps — retry pacing belongs to the caller: ``_request``'s
        bounded sleep loop, or the ActuationExecutor's reschedule.
        Thread-safe (workers call it concurrently)."""
        import requests

        try:
            r = self._transport(
                method, url, headers=self._headers(),
                json=body if method == "POST" else None,
                timeout=(CONNECT_TIMEOUT_S, READ_TIMEOUT_S))
        except requests.exceptions.RequestException as e:
            raise GcpRetryable(
                f"connection error ({e.__class__.__name__})",
                url=url) from e
        if r.status_code == 401:
            # Token revoked/rotated under us: invalidate so the retry
            # re-resolves (the provider single-flights the refresh).
            # attempt_free: both dispatch modes re-auth immediately,
            # exactly once, without burning a backoff attempt.
            self._tokens.invalidate()
            raise GcpRetryable("401 (re-resolving token)", http_status=401,
                               err_body=_parse_error_body(r), url=url,
                               attempt_free=True)
        if r.status_code in _RETRYABLE_STATUSES:
            raise GcpRetryable(str(r.status_code),
                               retry_after=r.headers.get("Retry-After"),
                               http_status=r.status_code,
                               err_body=_parse_error_body(r), url=url)
        if r.status_code >= 400:
            raise GcpApiError(r.status_code, url, _parse_error_body(r))
        return r.json() if r.content else {}

    # -- blocking mode ----------------------------------------------------

    def _request(self, method: str, url: str, body: dict | None) -> dict:
        reauthed = False
        attempt = 0
        while True:
            try:
                return self.once(method, url, body)
            except GcpRetryable as e:
                if e.http_status == 401:
                    if reauthed:
                        # Second 401: the fresh token is rejected too.
                        raise GcpApiError(
                            401, url,
                            e.err_body if e.err_body is not None
                            else "") from e
                    # Re-resolving the token doesn't burn a backoff
                    # attempt.
                    reauthed = True
                    self._note_retry(e.cause, url, attempt)
                    continue
                if attempt + 1 >= self.max_attempts:
                    raise e.terminal() from e
                self._note_retry(e.cause, url, attempt)
                self._sleep(self._backoff_seconds(attempt, e.retry_after))
                attempt += 1

    def get(self, url: str) -> dict:
        return self._request("GET", url, None)

    def post(self, url: str, body: dict) -> dict:
        if self.dry_run:
            log.info("[dry-run] POST %s %s", url, body)
            return {}
        return self._request("POST", url, body)

    def delete(self, url: str) -> dict:
        if self.dry_run:
            log.info("[dry-run] DELETE %s", url)
            return {}
        return self._request("DELETE", url, None)

    # -- pipelined mode ---------------------------------------------------

    def dispatch(self, executor: ActuationExecutor, method: str, url: str,
                 body: dict | None = None, *, on_done,
                 label: str = "") -> None:
        """Submit ONE call through the actuation executor (non-blocking).
        ``on_done(result, error)`` fires on the reconcile thread at a
        later ``drain()``.  Dry-run mutations resolve immediately with
        an empty result, mirroring the blocking verbs."""
        if self.dry_run and method in ("POST", "DELETE"):
            log.info("[dry-run] %s %s %s", method, url, body or "")
            on_done({}, None)
            return
        executor.submit(functools.partial(self.once, method, url, body),
                        on_done, label=label)
