"""Shared GCP REST plumbing for the real actuators (L1).

Analog of the reference's deployments.py (ARM submit/poll) — the thin,
serializable cloud-API layer under the scalers.  Deliberately SDK-free:
``requests`` + a bearer token cover the three verbs we need (POST create,
GET poll, DELETE teardown).  Token resolution order:

1. ``GCP_ACCESS_TOKEN`` env (operator-provided, e.g. `gcloud auth
   print-access-token`),
2. GCE/GKE metadata server (workload identity / attached SA) — the
   in-cluster path, mirroring how the reference used in-cluster service
   credentials.

Tokens are cached until ~5 minutes before expiry.
"""

from __future__ import annotations

import logging
import os
import time

log = logging.getLogger(__name__)

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


class GcpAuthError(RuntimeError):
    pass


class TokenProvider:
    def __init__(self):
        self._token: str | None = None
        self._expires_at = 0.0
        self._env_token_used: str | None = None

    def token(self) -> str:
        if self._token and time.time() < self._expires_at - 300:
            return self._token
        env = os.environ.get("GCP_ACCESS_TOKEN")
        if env and env != self._env_token_used:
            # A fresh operator-provided token (gcloud tokens live <=1h);
            # once it ages out we do NOT silently re-adopt the same stale
            # value — we fall through to the metadata server instead.
            self._env_token_used = env
            self._token, self._expires_at = env, time.time() + 3000
            return env
        try:
            import requests

            r = requests.get(_METADATA_TOKEN_URL,
                             headers={"Metadata-Flavor": "Google"},
                             timeout=5)
            r.raise_for_status()
            data = r.json()
            self._token = data["access_token"]
            self._expires_at = time.time() + float(
                data.get("expires_in", 3600))
            return self._token
        except Exception as e:  # noqa: BLE001
            if env:
                # No metadata server but the operator gave us a token:
                # keep using it (it may be long-lived), but say so.
                log.warning("GCP_ACCESS_TOKEN is older than its assumed "
                            "lifetime and no metadata server is available; "
                            "continuing with the possibly-stale token")
                self._token, self._expires_at = env, time.time() + 3000
                return env
            raise GcpAuthError(
                "no GCP credentials: set GCP_ACCESS_TOKEN or run with a "
                "metadata server (GKE workload identity)") from e


class GcpRest:
    """Minimal authenticated JSON REST client with dry-run support."""

    def __init__(self, dry_run: bool = False,
                 token_provider: TokenProvider | None = None):
        self.dry_run = dry_run
        self._tokens = token_provider or TokenProvider()

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self._tokens.token()}",
                "Content-Type": "application/json"}

    def get(self, url: str) -> dict:
        import requests

        r = requests.get(url, headers=self._headers(), timeout=30)
        r.raise_for_status()
        return r.json()

    def post(self, url: str, body: dict) -> dict:
        if self.dry_run:
            log.info("[dry-run] POST %s %s", url, body)
            return {}
        import requests

        r = requests.post(url, headers=self._headers(), json=body,
                          timeout=30)
        r.raise_for_status()
        return r.json()

    def delete(self, url: str) -> dict:
        if self.dry_run:
            log.info("[dry-run] DELETE %s", url)
            return {}
        import requests

        r = requests.delete(url, headers=self._headers(), timeout=30)
        r.raise_for_status()
        return r.json() if r.content else {}
