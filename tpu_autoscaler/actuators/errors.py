"""Machine-readable provisioning-failure taxonomy.

The reference surfaced cloud failures as raw exception strings in logs
(deployments.py); here every FAILED provision is classified into a small
closed vocabulary so the controller can (a) count failures per cause in
metrics, (b) stamp the cause on the starved pods' UNSATISFIABLE
annotation, and (c) let policy distinguish "wait it out" (stockout) from
"never going to work" (bad shape, permission).

Categories:

- ``stockout``   — the zone has no capacity for the shape right now
                   (GCE_STOCKOUT / resource pool exhausted / QR denied
                   for capacity).  Retry/generation-fallback territory.
- ``quota``      — the PROJECT's capacity quota is exhausted; retrying
                   won't help until quota changes.  Per-MINUTE rate
                   quotas are deliberately NOT this: they self-heal
                   within a backoff window, so they classify as
                   ``transient`` (ADVICE r5 #1).
- ``permission`` — auth/IAM (401/403 PERMISSION_DENIED).
- ``bad-shape``  — the request itself is invalid (unknown machine type /
                   accelerator / topology; 400 INVALID_ARGUMENT).
- ``transient``  — retries exhausted on 429/5xx/connection errors; the
                   next reconcile pass will try again.
- ``unknown``    — anything else.

Fixture-pinned in tests/test_actuator_fixtures.py against sanitized
real-shape GKE/Cloud-TPU response JSON.
"""

from __future__ import annotations

STOCKOUT_MARKERS = (
    "gce_stockout",
    "resource pool exhausted",
    "zone_resource_pool_exhausted",
    "does not have enough resources",
    "no more capacity",
    "out of capacity",
    "stockout",
    "resource_exhausted_for_capacity",
)

QUOTA_MARKERS = (
    "quota",
    "limit exceeded for",
)

# Rate limiting is a QUOTA in GCP's vocabulary ("Quota exceeded for
# quota metric ... per minute") but transient in ours: it clears within
# a backoff window, so policy must keep retrying instead of giving up.
# Checked BEFORE the quota bucket.
RATE_LIMIT_MARKERS = (
    "rate_limit_exceeded",
    "ratelimitexceeded",
    "rate limit",
    "per minute",
)

PERMISSION_MARKERS = (
    "permission_denied",
    "permission denied",
    "forbidden",
    "unauthenticated",
    "caller does not have permission",
    "access not configured",
)

BAD_SHAPE_MARKERS = (
    "invalid_argument",
    "invalid machine type",
    "machine type with name",
    "accelerator type",
    "not found in zone",
    "unsupported tpu topology",
    "invalid value for field",
)

# No bare numeric HTTP-status substrings here: "401"/"503" would match
# inside unrelated numbers ("after 4013ms").  Status codes classify via
# GcpApiError.http_status, which carries them reliably.
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "internal error",
    "backend error",
    "connection error",
    "timed out",
)

_TRANSIENT_HTTP = frozenset({408, 429, 500, 502, 503, 504})


def classify_provision_error(error) -> str:
    """Map a provisioning failure (exception, GcpApiError, or raw
    string) to one taxonomy category.

    Order matters: quota wording often contains "exhausted" and stockout
    wording often contains "resource", so the most specific markers are
    checked first within each category's own list, and stockout (the
    actionable, expected-in-production case) is checked before the
    generic buckets.
    """
    from tpu_autoscaler.actuators.gcp import GcpApiError

    text_parts = [str(error)]
    if isinstance(error, GcpApiError):
        text_parts += [error.status, error.message, *error.reasons]
    text = " ".join(text_parts).lower()
    # Rate limits first: GCP serves them as 403s with quota wording
    # ("Quota exceeded for quota metric ... per minute",
    # rateLimitExceeded), which would otherwise land in the permission
    # or quota buckets — both documented as not-retryable.
    if any(m in text for m in RATE_LIMIT_MARKERS):
        return "transient"
    if isinstance(error, GcpApiError):
        if error.http_status in (401, 403) and not any(
                m in text for m in QUOTA_MARKERS):
            return "permission"
        if error.http_status == 400:
            return "bad-shape"
    if any(m in text for m in STOCKOUT_MARKERS):
        return "stockout"
    if any(m in text for m in QUOTA_MARKERS):
        return "quota"
    if any(m in text for m in PERMISSION_MARKERS):
        return "permission"
    if any(m in text for m in BAD_SHAPE_MARKERS):
        return "bad-shape"
    if isinstance(error, GcpApiError) \
            and error.http_status in _TRANSIENT_HTTP:
        return "transient"
    if any(m in text for m in TRANSIENT_MARKERS):
        return "transient"
    return "unknown"
