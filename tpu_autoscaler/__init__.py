"""tpu_autoscaler — a TPU-native Kubernetes cluster autoscaler.

A brand-new framework with the capabilities of
``wbuchwalter/Kubernetes-acs-engine-autoscaler`` (an Azure acs-engine
autoscaler forked from OpenAI's kubernetes-ec2-autoscaler), redesigned from
scratch for Cloud TPU / GKE:

- Pending JAX workloads requesting ``google.com/tpu`` chips (single pods,
  gang-scheduled multi-host JobSets, multi-slice jobs) are detected and fit
  against a slice-shape/topology catalog (``tpu_autoscaler.topology``).
- Exactly-fitting TPU pod slices are provisioned via GKE node pools or Cloud
  TPU QueuedResources (``tpu_autoscaler.actuators``).
- Scale-down is slice-atomic and checkpoint-aware: a running ``pjit``/``pmap``
  job is never bisected, and idle slices are drained as whole ICI domains
  (``tpu_autoscaler.state``, ``tpu_autoscaler.controller``).

Layer map (analog of reference layers, see SURVEY.md §2):

====  =============================  =====================================
L5    CLI / process entry            ``tpu_autoscaler.main``
L4    Control loop / policy          ``tpu_autoscaler.controller``
L3a   Kubernetes model               ``tpu_autoscaler.k8s``
L3b   Capacity model                 ``tpu_autoscaler.topology``
L2    Scaling backends               ``tpu_autoscaler.actuators``
L1    Cloud plumbing                 ``tpu_autoscaler.actuators.rest``
L0    External systems               k8s API, GKE/TPU API, Slack
====  =============================  =====================================

Reference parity citations use ``<file> §<symbol>`` granularity because the
reference mount was empty when surveyed (SURVEY.md §0).
"""

__version__ = "0.1.0"
