"""``python -m tpu_autoscaler`` entry point."""

from tpu_autoscaler.main import cli

if __name__ == "__main__":
    cli()
