"""Dimensioned-quantity aliases for the cost algebra (ISSUE 16).

The control plane's money-denominated guards — the ledger's seven-state
conservation identity, repack's never-costs-more-than-it-saves budget,
the prewarm waste self-mute — all compute in four base units: chips,
seconds, chip-seconds and dollars, with one rate ($/chip-hour) joining
them.  A silent chips-vs-chip-seconds or per-hour-vs-per-second slip
corrupts exactly those guards, so every accumulator, budget window and
rate in the cost spine declares its dimension with one of the aliases
below, and the whole-program TAU10xx checker (analysis/units.py) audits
the algebra statically.

Why ``Annotated`` type aliases rather than ``NewType``: the aliases
must be *zero* runtime cost (no wrapper calls in the reconcile hot
path) and *transparent* to mypy's strict islands — a ``NewType`` would
turn every legitimate arithmetic reassignment (``usd_total += cs *
rate / 3600.0``) into a type error, forcing casts that hide exactly
the crossings the checker wants to see.  ``Annotated[float, "..."]``
erases to ``float`` for mypy and the interpreter alike; the TAU
checker reads the alias *names* from the AST and runs its own
dimension algebra over them.

The dimension lattice (exponents over chip ``c``, second ``s``, hour
``H``, dollar ``u``)::

    Chips          c
    Seconds        s
    ChipSeconds    c.s
    UsdPerChipHour u.c^-1.H^-1
    Usd            u
    Fraction       1          (known-dimensionless; ratios, confidences)

Multiplication adds exponent vectors, division subtracts them, and the
literal ``3600`` (or :data:`SECONDS_PER_HOUR`) carries ``s.H^-1`` —
so ``cs * rate / 3600.0`` lands on ``u`` exactly, while the classic
per-hour × seconds *without* the ``/3600`` leaves a mixed ``s``/``H``
residue the checker reports as TAU1002.

The two functions below are the only *blessed* dimension-crossing
constructors: prefer them at the load-bearing crossings so the intent
is explicit in the code, not just in the checker's algebra.
"""

from __future__ import annotations

from typing import Annotated, Final, TypeAlias

#: Whole chips of one accelerator unit (dimension ``c``).
Chips: TypeAlias = Annotated[int, "chip"]

#: Wall-clock or injected-clock seconds (dimension ``s``).
Seconds: TypeAlias = Annotated[float, "second"]

#: Chip-seconds — the fleet's native cost/budget currency (``c.s``).
ChipSeconds: TypeAlias = Annotated[float, "chip_second"]

#: Price-book rate in dollars per chip-hour (``u.c^-1.H^-1``).
UsdPerChipHour: TypeAlias = Annotated[float, "usd_per_chip_hour"]

#: Dollar proxy totals (``u``).
Usd: TypeAlias = Annotated[float, "usd"]

#: Known-dimensionless ratio in ``[0, 1]``-ish (confidences,
#: utilizations, savings ratios).  Distinct from an *unannotated*
#: float: the checker treats ``Fraction`` as proven dimensionless and
#: will flag it added to a dimensioned quantity.
Fraction: TypeAlias = Annotated[float, "fraction"]

#: The one unit-conversion constant (dimension ``s.H^-1``): dividing a
#: ``$/chip-hour x chip-seconds`` product by it yields dollars.
SECONDS_PER_HOUR: Final[float] = 3600.0


def chip_seconds(chips: Chips, seconds: Seconds) -> ChipSeconds:
    """Blessed ``c x s -> c.s`` crossing: the cost of holding
    ``chips`` for ``seconds``."""
    return chips * seconds


def usd(rate: UsdPerChipHour, cs: ChipSeconds) -> Usd:
    """Blessed ``u.c^-1.H^-1 x c.s -> u`` crossing: price
    chip-seconds at a $/chip-hour rate (the ONE place the 3600
    timebase conversion lives)."""
    return rate * cs / SECONDS_PER_HOUR
