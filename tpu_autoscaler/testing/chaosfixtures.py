"""Fuzzer-found failures, promoted to seeded regression fixtures
(racefixtures-style; docs/CHAOS.md seed-triage workflow).

Each fixture names the ORIGINATING seed and a ``sabotage`` hook that
re-opens the fixed bug on a live controller, so one test asserts both
directions: the chaos engine's invariants CATCH the bug class when
present (the detector earns its keep), and the shipped code holds
under the exact seed that found it.

These are FIXTURES: the sabotage hooks intentionally reintroduce bugs.
Never call them outside tests.
"""

from __future__ import annotations

import dataclasses

from tpu_autoscaler.chaos.engine import ChaosResult, _Run
from tpu_autoscaler.chaos.scenario import ScenarioProgram, generate


@dataclasses.dataclass(frozen=True)
class ChaosRegression:
    name: str
    seed: int
    profile: str
    invariant: str           # the invariant the original failure tripped
    description: str
    #: Run with the ISSUE 13 sharded planner attached (0 = serial).
    #: Shard fixtures need the fan-out/merge path live to re-open
    #: their bug class.
    reconcile_shards: int = 0

    def program(self) -> ScenarioProgram:
        # multislice=False: these fixtures pin the exact pre-ISSUE-8
        # seed programs that found their bugs.
        return generate(self.seed, profile=self.profile,
                        multislice=False)

    def run(self, sabotage=None) -> ChaosResult:
        run = _Run(self.program(),
                   reconcile_shards=self.reconcile_shards)
        if sabotage is not None:
            sabotage(run)
        return run.execute()


def _lose_dispatch_roots(run: _Run) -> None:
    """Pre-fix emulation for LATE_PROVISION_SPAN: drop the dispatch-time
    trace-root capture, so a provision resolving after its gang's trace
    closed records no provision span (the original bug)."""
    controller = run.controller
    original = controller.reconcile_once

    def reconcile_once(now=None):
        controller._provision_roots.clear()
        return original(now=now)

    controller.reconcile_once = reconcile_once


def _disable_orphan_reclaim(run: _Run) -> None:
    """Pre-fix emulation for ORPHANED_PARTIAL_SLICE: a provision that
    FAILs after materializing some hosts leaks them forever."""
    run.controller._reclaim_if_orphaned = \
        lambda unit_id, unit_nodes, unit_pods, now: None


def _disable_repair_deferral(run: _Run) -> None:
    """Pre-fix emulation for GANG_SPLIT_BACKFILL: no repair subsystem —
    no whole-gang deferral, no advisory replacement — so a recreated
    member of a broken slice is sized SOLO and the gang converges split
    across ICI domains.  The fake scheduler's pre-ISSUE-13 first-fit
    semantics are restored too: the world model's multi-host slice
    exclusivity (REPAIR_FOREIGN_SLICE_BIND's fix) independently masks
    this symptom in the FAKE, but real schedulers are not
    gang-exclusive — the controller-layer deferral stays load-bearing,
    and this fixture pins it under the original bug's environment."""
    run.kube._gang_exclusive = False
    run.controller.config = dataclasses.replace(
        run.controller.config, enable_slice_repair=False,
        unhealthy_timeout_seconds=60.0)
    run.controller._repair_advisory = \
        lambda nodes, pods, gangs, now: ([], set())


#: A provision can go ACTIVE/FAILED after its gang's trace closed (the
#: gang ran off other supply); its span must still land in the trace
#: that dispatched it.  Fixed by dispatch-time trace-root capture
#: (reconciler._provision_roots).
LATE_PROVISION_SPAN = ChaosRegression(
    name="late-provision-span", seed=4, profile="mixed",
    invariant="trace-completeness",
    description="provision resolves after the scale-up trace closed; "
                "span lost without dispatch-time root capture")

#: A mid-provision stockout against a staggered slice leaves partially
#: materialized hosts with no backing provision; nothing reclaimed them.
#: Fixed by the orphaned-partial-unit reclaim
#: (reconciler._reclaim_if_orphaned).
ORPHANED_PARTIAL_SLICE = ChaosRegression(
    name="orphaned-partial-slice", seed=1, profile="mixed",
    invariant="no-stranded-chips",
    description="FAILED provision strands partially materialized hosts "
                "behind the barrier forever")

#: A host deleted from a live slice gets its pod recreated; without the
#: whole-gang repair deferral the lone member is sized solo and the
#: gang converges split across two ICI domains.  Fixed by the repair
#: subsystem's advisory demand + solo-planning deferral.
GANG_SPLIT_BACKFILL = ChaosRegression(
    name="gang-split-backfill", seed=13, profile="repair",
    invariant="gang-ici-integrity",
    description="recreated member of a broken slice planned solo; gang "
                "runs split across slices")


def _disable_budget_guard(run: _Run) -> None:
    """Pre-fix emulation for REPACK_GUARDLESS_LOSS: no in-flight
    budget guard and no misfire accounting — the original design gap.
    A spot_dry racing the migration leaves the drain running; the
    advisory replacement then provisions FRESH on-demand supply, the
    gang lands on it, and the migration completes silently
    net-negative instead of aborting the moment its destination
    vanished (ISSUE 12, docs/REPACK.md "The savings guarantee")."""
    run.controller._guard_repacks = lambda *a, **k: None
    repacker = run.controller.repacker
    orig_inc = repacker._inc
    repacker._inc = lambda name, by=1.0: (
        None if name == "repack_misfires" else orig_inc(name, by))


#: A repack migration whose destination spot slice vanishes mid-drain
#: must ABORT (budget guard: projected savings collapse to zero), not
#: complete onto freshly-provisioned expensive supply.  Seed 15's
#: program races spot_dry into the migration window: the shipped code
#: aborts (repack_migrations_aborted >= 1, zero violations); with the
#: guard + misfire accounting sabotaged away, the run completes a
#: silent net-negative migration and the never-net-negative invariant
#: catches it.
REPACK_GUARDLESS_LOSS = ChaosRegression(
    name="repack-guardless-loss", seed=15, profile="repack",
    invariant="repack-never-net-negative",
    description="destination spot slice dries up mid-migration; "
                "without the budget guard the migration completes "
                "net-negative on fresh on-demand supply, silently")

def _double_merge_request(run: _Run) -> None:
    """Pre-fix emulation for SHARD_DOUBLE_MERGE: a mis-merge that
    reassembles one shard's organic request twice — the bug class the
    merge point's order/conflict re-validation exists to prevent
    (ISSUE 13, docs/SHARDING.md "Merge-point semantics").  The same
    gang gets two provisions dispatched in one pass."""
    sharder = run.controller.sharder
    assert sharder is not None, "shard fixture needs reconcile_shards"
    orig = sharder.plan

    def plan(*args, **kwargs):
        out = orig(*args, **kwargs)
        if sharder.last_info.get("mode") == "sharded":
            dup = next((r for r in out.requests
                        if r.kind == "tpu-slice"
                        and r.gang_key is not None), None)
            if dup is not None:
                out.requests.append(dup)
        return out

    sharder.plan = plan


#: The sharded merge must never reassemble a shard's request twice (or
#: admit two shards' requests for one gang): the planner-visible
#: in-flight ledger would carry two entries for one gang key — exactly
#: the no-double-provision invariant.  The shipped merge (original-
#: order reassembly + conflict re-validation) holds under the seed
#: with 4 shards attached; the sabotaged mis-merge is caught.
SHARD_DOUBLE_MERGE = ChaosRegression(
    name="shard-double-merge", seed=7, profile="mixed",
    invariant="no-double-provision",
    description="a mis-merged sharded plan dispatches one gang's "
                "provision twice in a single pass",
    reconcile_shards=4)

def _first_fit_scheduling(run: _Run) -> None:
    """Pre-fix emulation for REPAIR_FOREIGN_SLICE_BIND: the fake
    scheduler's pre-ISSUE-13 first-fit candidates — no multi-host
    slice exclusivity, no bind-only-within-held-slices rule."""
    run.kube._gang_exclusive = False


#: Found by the ISSUE 13 sharded repair corpus (seed 85, latent since
#: PR 7 — the repair corpus was not in CI): a host_fail evicts one
#: member of a running gang; the recreated pod bound FIRST-FIT beside
#: a DIFFERENT gang on a half-free multi-host slice, where its
#: siblings could never follow (3 free hosts, 7 needed) — the gang
#: converged split across two ICI domains.  Fixed in the world model
#: (FakeKube.schedule_step): multi-host slices are exclusively
#: scheduled (GKE TPU semantics) and a gang holding slices binds only
#: within them, so the stray member waits for the repair replacement
#: instead of splitting the domain.
REPAIR_FOREIGN_SLICE_BIND = ChaosRegression(
    name="repair-foreign-slice-bind", seed=85, profile="repair",
    invariant="gang-ici-integrity",
    description="recreated member of a broken slice binds first-fit "
                "beside a foreign gang; its siblings can never "
                "follow and the gang converges split")

SABOTAGE = {
    LATE_PROVISION_SPAN.name: _lose_dispatch_roots,
    ORPHANED_PARTIAL_SLICE.name: _disable_orphan_reclaim,
    GANG_SPLIT_BACKFILL.name: _disable_repair_deferral,
    REPACK_GUARDLESS_LOSS.name: _disable_budget_guard,
    SHARD_DOUBLE_MERGE.name: _double_merge_request,
    REPAIR_FOREIGN_SLICE_BIND.name: _first_fit_scheduling,
}

ALL_REGRESSIONS = (LATE_PROVISION_SPAN, ORPHANED_PARTIAL_SLICE,
                   GANG_SPLIT_BACKFILL, REPACK_GUARDLESS_LOSS,
                   SHARD_DOUBLE_MERGE, REPAIR_FOREIGN_SLICE_BIND)
