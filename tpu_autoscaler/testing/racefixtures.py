"""Seeded-bug fixtures for the race detector's cross-validation tests.

The two layers of the detector must each catch something the other is
blind to (docs/ANALYSIS.md):

- ``DynamicCounter``/``hammer`` is the STATIC-BLIND fixture: the
  mutating call is resolved through ``getattr`` at runtime, so the
  TAR5xx call graph cannot connect the thread root to the write — the
  static pass reports NOTHING on this module (asserted in
  tests/test_sched.py), while the deterministic-schedule harness flags
  the unsynchronized ``value`` access within its seeded budget.

- ``LeakyCache``/``leaky_informer_scenario`` is the injected
  informer/executor-shaped race: a watch-thread-fed cache whose
  ``apply`` path skips the lock — exactly the bug class the real
  ``ObjectCache`` guards against — driven through a real
  ``ResourceWatch`` thread so the harness proves it catches the bug in
  production-shaped plumbing, not only in toy classes.

These are FIXTURES: intentionally racy, never imported by production
code.  Do not "fix" them.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Mapping

from tpu_autoscaler import concurrency


class DynamicCounter:
    """Racy on purpose; invisible to the static pass (getattr dispatch).

    ``value`` is read-modify-written with no lock, but the only path to
    ``bump`` goes through ``poke``'s ``getattr(self, self._op)`` — an
    edge no sound-by-evidence call graph can resolve.
    """

    def __init__(self) -> None:
        self._op = "bump"
        self.value = 0

    def bump(self) -> None:
        self.value = self.value + 1

    def poke(self) -> None:
        getattr(self, self._op)()


def hammer(counter: DynamicCounter, rounds: int = 2) -> None:
    """Poke ``counter`` from a spawned thread and the caller at once."""
    t = concurrency.Thread(target=counter.poke)
    t.start()
    for _ in range(rounds):
        counter.poke()
    t.join()


class LeakyCache:
    """An ObjectCache-shaped store whose delta path SKIPS the lock.

    ``replace`` (the relist path) takes the lock like the real informer
    cache; ``apply`` (the hot watch-delta path) does not — the seeded
    informer bug layer 2 must flag.
    """

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self._lock = concurrency.Lock()
        self._objects: dict[str, dict] = {}
        self.version: str | None = None

    def replace(self, items: Iterable[dict],
                resource_version: str | None) -> None:
        objects = {i["metadata"]["name"]: i for i in items}
        with self._lock:
            self._objects = objects
            self.version = resource_version

    def apply(self, event: Mapping[str, Any]) -> bool:
        obj = dict(event.get("object") or {})
        name = obj.get("metadata", {}).get("name")
        if name is None:
            return False
        # BUG (seeded): no lock around the mutation or the cursor bump.
        self._objects[name] = obj  # analysis: allow=TAT201 seeded-bug fixture (the bug is the point)
        self.version = obj.get("metadata", {}).get("resourceVersion")  # analysis: allow=TAT201 seeded-bug fixture
        return True

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._objects.values())

    def peek_version(self) -> str | None:
        # BUG (seeded): unlocked read racing apply()'s unlocked write.
        return self.version


def drive_leaky_cache(cache: LeakyCache,
                      events: list[Mapping[str, Any]],
                      reads: int,
                      spawn: Callable[..., Any] = concurrency.Thread) -> None:
    """Feed ``events`` through a background thread while the caller
    reads — the minimal informer-shaped drive for the seeded bug."""
    def feeder() -> None:
        for event in events:
            cache.apply(event)

    t = spawn(target=feeder)
    t.start()
    for _ in range(reads):
        cache.peek_version()
        cache.snapshot()
    t.join()
