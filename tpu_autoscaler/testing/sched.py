"""Deterministic-schedule concurrency harness (race detector layer 2).

Layer 1 (``tpu_autoscaler/analysis/escape.py``) proves the *quiet*
paths statically; this module proves the *loud* ones by running them.
It drives real production code — informer watch threads, the actuation
executor's workers, the reconcile loop — under a deterministic
scheduler:

- every ``Thread``/``Lock``/``RLock``/``Event``/``Condition``/worker
  pool constructed through the ``tpu_autoscaler.concurrency`` seam
  while a scheduler is active becomes scheduler-controlled;
- execution is fully SERIALIZED: exactly one managed thread runs at a
  time, and at every synchronization point (lock acquire/release,
  event set/wait/is_set, thread start/join, tracked-attribute write)
  the scheduler picks the next thread to run from a seeded RNG — so a
  scenario replayed with the same seed takes the same interleaving,
  and sweeping seeds systematically permutes interleavings;
- timeouts are *schedule choices*, not wall-clock: a thread in a timed
  wait can be woken by its signal or "expired" by the scheduler
  (always expired when nothing else is runnable — virtual time), so
  scenarios terminate without sleeping;
- a vector-clock happens-before checker watches attribute accesses on
  objects registered via ``tracker.track(obj)``: two accesses to the
  same attribute, at least one a write, from different threads, with
  disjoint locksets and no happens-before edge between them, are a
  data race — reported with BOTH stacks regardless of whether the
  explored interleaving actually corrupted anything.

Scenario shape::

    def scenario(sched):
        cache = sched.tracker.track(ObjectCache("pods", parse_pod))
        w = ResourceWatch(cache, list_fn, watch_fn)   # concurrency.Thread
        w.start()
        ... drive the reconcile side ...
        w.stop()

    races = find_races(scenario, schedules=20)
    assert not races, races[0].describe()

The harness is single-core honest: it cannot observe true parallelism,
but any unsynchronized conflicting pair IS caught by the happens-before
check in whichever schedule makes both accesses happen — that is the
point of sweeping seeds.
"""

from __future__ import annotations

import contextlib
import itertools
import random
import sys
import threading as _threading
import traceback
from typing import Any, Callable, Iterator

import concurrent.futures

from tpu_autoscaler import concurrency

#: Managed-thread states.
RUNNABLE = "runnable"
BLOCKED = "blocked"      # only a state change (release/set/exit) wakes it
TIMED = "timed"          # timed wait: a signal OR a schedule choice wakes it
DONE = "done"


class SchedulerError(RuntimeError):
    pass


class DeadlockError(SchedulerError):
    """Every managed thread is blocked and no timed wait can expire."""


class StepBudgetExceeded(SchedulerError):
    """The schedule ran past ``max_steps`` sync points (livelock guard)."""


class _Shutdown(BaseException):
    """Unwinds managed threads at scheduler teardown.  BaseException on
    purpose: the control plane's crash-only ``except Exception`` loops
    must not be able to swallow it."""


def _vc_join(dst: dict[int, int], src: dict[int, int]) -> None:
    for k, v in src.items():
        if v > dst.get(k, 0):
            dst[k] = v


def _vc_hb(a: dict[int, int], b: dict[int, int]) -> bool:
    """True iff clock ``a`` happened-before (or equals) clock ``b``."""
    return all(v <= b.get(k, 0) for k, v in a.items())


class _TCB:
    """One managed thread's control block."""

    __slots__ = ("tid", "name", "sem", "state", "vc", "locks",
                 "waiting_on", "wake_flag", "crash")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.sem = _threading.Semaphore(0)
        self.state = RUNNABLE
        self.vc: dict[int, int] = {tid: 1}
        self.locks: list[Any] = []      # held SchedLock/SchedRLock, in order
        self.waiting_on: Any = None
        self.wake_flag = False
        self.crash: BaseException | None = None


# --------------------------------------------------------------------- #
# Shim primitives (constructed via the concurrency seam)
# --------------------------------------------------------------------- #

class SchedLock:
    def __init__(self, sched: "DeterministicScheduler", reentrant: bool):
        self._sched = sched
        self._reentrant = reentrant
        self._owner: _TCB | None = None
        self._count = 0
        self.vc: dict[int, int] = {}

    def acquire(self, blocking: bool = True,
                timeout: float | None = None) -> bool:
        if timeout is not None and timeout != -1:
            # Timed lock acquisition is not modeled (nothing behind the
            # seam uses it); failing loudly beats silently blocking
            # forever and reporting a spurious deadlock.
            raise NotImplementedError(
                "timed Lock.acquire is not modeled by the scheduler")
        return self._sched.lock_acquire(self, blocking)

    def release(self) -> None:
        self._sched.lock_release(self)

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class SchedEvent:
    def __init__(self, sched: "DeterministicScheduler"):
        self._sched = sched
        self._set = False
        self.vc: dict[int, int] = {}

    def is_set(self) -> bool:
        self._sched.step()
        return self._set

    def set(self) -> None:
        self._sched.event_set(self)

    def clear(self) -> None:
        self._sched.step()
        self._set = False

    def wait(self, timeout: float | None = None) -> bool:
        return self._sched.event_wait(self, timeout)


class SchedCondition:
    def __init__(self, sched: "DeterministicScheduler", lock=None):
        self._sched = sched
        self._lock = lock if lock is not None else SchedLock(sched, True)

    def acquire(self, *a: Any) -> bool:
        return self._lock.acquire()

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "SchedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        return self._sched.cond_wait(self, timeout)

    def notify(self, n: int = 1) -> None:
        self._sched.cond_notify(self, n)

    def notify_all(self) -> None:
        self._sched.cond_notify(self, sys.maxsize)


class SchedPool:
    """ThreadPoolExecutor stand-in: each submitted thunk runs as its own
    managed thread (the worker cap is a throughput knob, irrelevant to
    interleaving coverage, so it is not modeled)."""

    def __init__(self, sched: "DeterministicScheduler", max_workers: int):
        self._sched = sched
        self._ids = itertools.count(1)

    def submit(self, fn: Callable[..., Any], /, *args: Any,
               **kwargs: Any) -> "concurrent.futures.Future[Any]":
        fut: concurrent.futures.Future[Any] = concurrent.futures.Future()
        fut.set_running_or_notify_cancel()

        def run() -> None:
            try:
                fut.set_result(fn(*args, **kwargs))
            except _Shutdown:
                raise
            except BaseException as e:  # noqa: BLE001 — future protocol
                fut.set_exception(e)

        self._sched.spawn(run, name=f"pool-worker-{next(self._ids)}")
        return fut

    def shutdown(self, wait: bool = True,
                 cancel_futures: bool = False) -> None:
        pass  # managed threads are unwound at scheduler teardown


# --------------------------------------------------------------------- #
# Happens-before access tracker
# --------------------------------------------------------------------- #

class _Access:
    __slots__ = ("tid", "thread", "op", "vc", "lockset", "where", "stack")

    def __init__(self, tid: int, thread: str, op: str, vc: dict[int, int],
                 lockset: frozenset, where: str, stack: str):
        self.tid = tid
        self.thread = thread
        self.op = op
        self.vc = vc
        self.lockset = lockset
        self.where = where
        self.stack = stack


class RaceReport:
    def __init__(self, cls: str, attr: str, a: _Access, b: _Access):
        self.cls = cls
        self.attr = attr
        self.a = a
        self.b = b

    def describe(self) -> str:
        return (
            f"data race on {self.cls}.{self.attr}: "
            f"{self.a.op} by '{self.a.thread}' at {self.a.where} vs "
            f"{self.b.op} by '{self.b.thread}' at {self.b.where} "
            f"(disjoint locksets, no happens-before edge)\n"
            f"--- first access stack ---\n{self.a.stack}"
            f"--- second access stack ---\n{self.b.stack}")

    def __repr__(self) -> str:
        return (f"RaceReport({self.cls}.{self.attr}: {self.a.thread} "
                f"{self.a.op} vs {self.b.thread} {self.b.op})")


_SYNC_TYPES = (SchedLock, SchedEvent, SchedCondition,
               type(_threading.Lock()), type(_threading.RLock()),
               _threading.Event, _threading.Condition,
               _threading.Semaphore)

_MAX_ACCESSES_PER_KEY = 64
_MAX_RACES = 100


class AccessTracker:
    """Records reads/writes on tracked objects and checks every
    conflicting pair for a happens-before edge and a common lock."""

    def __init__(self, sched: "DeterministicScheduler"):
        self._sched = sched
        self._busy = _threading.local()
        self._by_key: dict[tuple[int, str], list[_Access]] = {}
        self._cls_of: dict[int, str] = {}
        self._subclasses: dict[type, type] = {}
        self.races: list[RaceReport] = []

    def track(self, obj: Any) -> Any:
        """Swap ``obj``'s class for an access-recording subclass and
        return it.  The object behaves identically otherwise."""
        cls = obj.__class__
        sub = self._subclasses.get(cls)
        if sub is None:
            tracker = self

            def __setattr__(s: Any, name: str, value: Any) -> None:
                if not isinstance(value, _SYNC_TYPES):
                    tracker._record(s, name, "write")
                object.__setattr__(s, name, value)

            def __getattribute__(s: Any, name: str) -> Any:
                value = object.__getattribute__(s, name)
                tracker._maybe_record_read(s, name, value)
                return value

            sub = type("Tracked" + cls.__name__, (cls,), {
                "__setattr__": __setattr__,
                "__getattribute__": __getattribute__,
            })
            self._subclasses[cls] = sub
        self._cls_of[id(obj)] = cls.__name__
        obj.__class__ = sub
        return obj

    def _maybe_record_read(self, obj: Any, name: str, value: Any) -> None:
        if name.startswith("__") or callable(value) \
                or isinstance(value, _SYNC_TYPES):
            return
        if name not in object.__getattribute__(obj, "__dict__"):
            return
        self._record(obj, name, "read")

    def _record(self, obj: Any, name: str, op: str) -> None:
        if getattr(self._busy, "flag", False):
            return
        tcb = self._sched.current_tcb()
        if tcb is None or name.startswith("__"):
            return
        self._busy.flag = True
        try:
            where, stack = self._caller()
            acc = _Access(
                tcb.tid, tcb.name, op, dict(tcb.vc),
                frozenset(id(lk) for lk in tcb.locks), where, stack)
            key = (id(obj), name)
            prior = self._by_key.setdefault(key, [])
            for prev in prior:
                if len(self.races) >= _MAX_RACES:
                    break
                if (prev.op == "write" or op == "write") \
                        and prev.tid != acc.tid \
                        and not (prev.lockset & acc.lockset) \
                        and not _vc_hb(prev.vc, acc.vc):
                    self.races.append(RaceReport(
                        self._cls_of.get(id(obj), type(obj).__name__),
                        name, prev, acc))
            prior.append(acc)
            del prior[:-_MAX_ACCESSES_PER_KEY]
        finally:
            self._busy.flag = False
        if op == "write":
            # A shared write is itself an interleaving point.
            self._sched.step()

    @staticmethod
    def _caller() -> tuple[str, str]:
        frame = sys._getframe(2)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>", ""
        where = (f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:"
                 f"{frame.f_lineno} ({frame.f_code.co_name})")
        stack = "".join(traceback.format_stack(frame, limit=6))
        return where, stack


# --------------------------------------------------------------------- #
# The scheduler
# --------------------------------------------------------------------- #

class DeterministicScheduler:
    """Serialized seeded-interleaving executor for seam-built threads."""

    def __init__(self, seed: int = 0, max_steps: int = 200_000):
        self.seed = seed
        self._rng = random.Random(seed)
        self._tids = itertools.count()
        self._tcbs: dict[int, _TCB] = {}
        self._local = _threading.local()
        self._threads: dict[int, _threading.Thread] = {}  # real backing
        self._adopted: dict[int, _TCB] = {}  # id(Thread obj) -> tcb
        self._main: _TCB | None = None
        self._steps = 0
        self._max_steps = max_steps
        self._shutdown = False
        self.tracker = AccessTracker(self)
        self.crashes: list[tuple[str, BaseException]] = []

    # -- seam factories ---------------------------------------------------

    def create_lock(self) -> SchedLock:
        return SchedLock(self, reentrant=False)

    def create_rlock(self) -> SchedLock:
        return SchedLock(self, reentrant=True)

    def create_event(self) -> SchedEvent:
        return SchedEvent(self)

    def create_condition(self, lock=None) -> SchedCondition:
        return SchedCondition(self, lock)

    def create_pool(self, max_workers: int) -> SchedPool:
        return SchedPool(self, max_workers)

    # -- lifecycle --------------------------------------------------------

    @contextlib.contextmanager
    def active(self) -> Iterator["DeterministicScheduler"]:
        """Install the scheduler and register the calling thread as the
        managed 'main' thread for the duration of the block."""
        concurrency.install_scheduler(self)
        main = _TCB(next(self._tids), "main")
        self._tcbs[main.tid] = main
        self._local.tcb = main
        self._main = main
        try:
            yield self
        finally:
            try:
                self._teardown(main)
            finally:
                self._local.tcb = None
                concurrency.install_scheduler(None)

    def _teardown(self, main: _TCB) -> None:
        self._shutdown = True
        for _ in range(10_000):
            live = [t for t in self._tcbs.values()
                    if t is not main and t.state != DONE]
            if not live:
                break
            for t in live:
                # Force-wake: the next sync point each thread hits will
                # raise _Shutdown and unwind it.
                if t.state in (BLOCKED, TIMED):
                    t.state = RUNNABLE
                    t.wake_flag = False
            self._switch(live[0])
        for rt in self._threads.values():
            rt.join(timeout=5.0)

    # -- thread management ------------------------------------------------

    def current_tcb(self) -> _TCB | None:
        return getattr(self._local, "tcb", None)

    def _cur(self) -> _TCB:
        tcb = self.current_tcb()
        if tcb is None:
            raise SchedulerError(
                "scheduler-managed primitive used from an unmanaged "
                "thread; create threads through the concurrency seam")
        return tcb

    def spawn(self, fn: Callable[[], Any], name: str) -> _TCB:
        parent = self._cur()
        tcb = _TCB(next(self._tids), name)
        _vc_join(tcb.vc, parent.vc)          # start() edge: parent → child
        tcb.vc[tcb.tid] = tcb.vc.get(tcb.tid, 0) + 1
        parent.vc[parent.tid] += 1
        self._tcbs[tcb.tid] = tcb
        real = _threading.Thread(
            target=self._managed_main, args=(tcb, fn),
            name=f"sched-{name}", daemon=True)
        self._threads[tcb.tid] = real
        real.start()
        self.step()                          # child may run immediately
        return tcb

    def adopt_thread(self, thread: _threading.Thread) -> None:
        tcb = self.spawn(thread.run, name=thread.name or "thread")
        self._adopted[id(thread)] = tcb

    def owns_thread(self, thread: _threading.Thread) -> bool:
        return id(thread) in self._adopted

    def join_thread(self, thread: _threading.Thread) -> None:
        cur = self._cur()
        target = self._adopted[id(thread)]
        while target.state != DONE:
            cur.state = BLOCKED
            cur.waiting_on = target
            self._wait_scheduled(cur)
        cur.waiting_on = None
        _vc_join(cur.vc, target.vc)          # join() edge: child → parent

    def _managed_main(self, tcb: _TCB, fn: Callable[[], Any]) -> None:
        self._local.tcb = tcb
        tcb.sem.acquire()                    # wait to be scheduled first
        if self._shutdown:
            self._finish(tcb)
            return
        try:
            fn()
        except _Shutdown:
            pass
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            # at find_races level; a silent dead thread would look like
            # a passing scenario.
            tcb.crash = e
            self.crashes.append((tcb.name, e))
        self._finish(tcb)

    def _finish(self, tcb: _TCB) -> None:
        tcb.state = DONE
        for t in self._tcbs.values():
            if t.state == BLOCKED and t.waiting_on is tcb:
                t.state = RUNNABLE           # wake joiners
        nxt = self._choose(exclude=tcb)
        if nxt is not None:
            self._handoff(nxt)
        else:
            main = self._main
            if main is not None and main.state != DONE:
                # Everyone else is blocked: the run ends in deadlock.
                self.crashes.append((tcb.name, DeadlockError(
                    "all threads blocked at thread exit")))
                main.state = RUNNABLE
                main.sem.release()

    # -- core scheduling --------------------------------------------------

    def step(self) -> None:
        """One scheduling decision point (preemption opportunity)."""
        cur = self.current_tcb()
        if cur is None:
            return
        self._check_shutdown(cur)
        self._steps += 1
        if self._steps > self._max_steps:
            raise StepBudgetExceeded(
                f"schedule exceeded {self._max_steps} sync points "
                f"(seed {self.seed}) — livelock or missing stop signal")
        nxt = self._choose()
        if nxt is not None and nxt is not cur:
            self._switch(nxt)

    def _check_shutdown(self, cur: _TCB) -> None:
        if self._shutdown and cur is not self._main:
            raise _Shutdown()

    def _choose(self, exclude: _TCB | None = None) -> _TCB | None:
        """Pick the next thread: any RUNNABLE, or a TIMED waiter (picking
        one means its timeout fires — timeouts are schedule choices)."""
        pool = [t for t in sorted(self._tcbs.values(), key=lambda t: t.tid)
                if t is not exclude and t.state in (RUNNABLE, TIMED)]
        if not pool:
            return None
        return self._rng.choice(pool)

    def _handoff(self, to: _TCB) -> None:
        if to.state == TIMED:
            to.state = RUNNABLE
            to.wake_flag = False             # its timeout fired
        to.sem.release()

    def _switch(self, to: _TCB) -> None:
        cur = self._cur()
        if to is cur:
            return
        self._handoff(to)
        cur.sem.acquire()                    # park until re-scheduled
        self._check_shutdown(cur)

    def _wait_scheduled(self, cur: _TCB) -> None:
        """Current thread is BLOCKED/TIMED: run someone else until a
        wake-up (or, for timed waits, a virtual-time expiry)."""
        nxt = self._choose(exclude=cur)
        if nxt is None:
            if cur.state == TIMED:
                cur.state = RUNNABLE         # nothing else can run: the
                cur.wake_flag = False        # timeout fires (virtual time)
                return
            raise DeadlockError(self._deadlock_report(cur))
        self._switch(nxt)

    def _deadlock_report(self, cur: _TCB) -> str:
        lines = [f"deadlock (seed {self.seed}): every thread is blocked"]
        for t in sorted(self._tcbs.values(), key=lambda t: t.tid):
            lines.append(f"  {t.name}: {t.state}"
                         + (f" on {type(t.waiting_on).__name__}"
                            if t.waiting_on is not None else ""))
        return "\n".join(lines)

    # -- primitive semantics ----------------------------------------------

    def lock_acquire(self, lock: SchedLock, blocking: bool) -> bool:
        cur = self._cur()
        self.step()                          # interleave BEFORE acquiring
        if lock._owner is cur:
            if not lock._reentrant:
                raise DeadlockError(
                    f"'{cur.name}' re-acquired a non-reentrant lock")
            lock._count += 1
            return True
        while lock._owner is not None:
            if not blocking:
                return False
            cur.state = BLOCKED
            cur.waiting_on = lock
            self._wait_scheduled(cur)
            self._check_shutdown(cur)
        cur.waiting_on = None
        lock._owner = cur
        lock._count = 1
        cur.locks.append(lock)
        _vc_join(cur.vc, lock.vc)            # release → acquire edge
        cur.vc[cur.tid] += 1
        return True

    def lock_release(self, lock: SchedLock) -> None:
        cur = self._cur()
        if lock._owner is not cur:
            raise SchedulerError(
                f"'{cur.name}' released a lock it does not hold")
        if lock._reentrant and lock._count > 1:
            lock._count -= 1
            return
        cur.vc[cur.tid] += 1
        _vc_join(lock.vc, cur.vc)
        lock._owner = None
        lock._count = 0
        if lock in cur.locks:
            cur.locks.remove(lock)
        for t in self._tcbs.values():
            if t.state == BLOCKED and t.waiting_on is lock:
                t.state = RUNNABLE           # re-contends in its loop
        self.step()

    def event_set(self, ev: SchedEvent) -> None:
        cur = self._cur()
        cur.vc[cur.tid] += 1
        _vc_join(ev.vc, cur.vc)              # set → wait edge
        ev._set = True
        for t in self._tcbs.values():
            if t.state in (BLOCKED, TIMED) and t.waiting_on is ev:
                t.state = RUNNABLE
                t.wake_flag = True
                t.waiting_on = None
        self.step()

    def event_wait(self, ev: SchedEvent, timeout: float | None) -> bool:
        cur = self._cur()
        self.step()
        if ev._set:
            _vc_join(cur.vc, ev.vc)
            return True
        if timeout is not None and timeout <= 0:
            return False
        cur.state = TIMED if timeout is not None else BLOCKED
        cur.waiting_on = ev
        cur.wake_flag = False
        while cur.state != RUNNABLE:
            self._wait_scheduled(cur)
        self._check_shutdown(cur)
        cur.waiting_on = None
        if cur.wake_flag or ev._set:
            _vc_join(cur.vc, ev.vc)
            return True
        return False                         # timeout fired

    def cond_wait(self, cond: SchedCondition, timeout: float | None) -> bool:
        cur = self._cur()
        lock = cond._lock
        if lock._owner is not cur:
            raise SchedulerError("cond.wait() without holding the lock")
        saved = lock._count
        lock._count = 1
        # Register as a waiter BEFORE releasing the lock (atomic in real
        # threading): a notify landing between release and registration
        # must not be lost.
        cur.state = TIMED if timeout is not None else BLOCKED
        cur.waiting_on = cond
        cur.wake_flag = False
        self.lock_release(lock)              # full release, waiters wake
        while cur.state != RUNNABLE:
            self._wait_scheduled(cur)
        self._check_shutdown(cur)
        cur.waiting_on = None
        woken = cur.wake_flag
        self.lock_acquire(lock, blocking=True)
        lock._count = saved
        return woken

    def cond_notify(self, cond: SchedCondition, n: int) -> None:
        cur = self._cur()
        cur.vc[cur.tid] += 1
        woken = 0
        for t in sorted(self._tcbs.values(), key=lambda t: t.tid):
            if woken >= n:
                break
            if t.state in (BLOCKED, TIMED) and t.waiting_on is cond:
                _vc_join(t.vc, cur.vc)       # notify → wake edge
                t.state = RUNNABLE
                t.wake_flag = True
                t.waiting_on = None
                woken += 1
        self.step()


# --------------------------------------------------------------------- #
# Entry points
# --------------------------------------------------------------------- #

def run_schedule(scenario: Callable[[DeterministicScheduler], None], *,
                 seed: int = 0,
                 max_steps: int = 200_000) -> DeterministicScheduler:
    """Run ``scenario`` under ONE seeded schedule; returns the scheduler
    (``.tracker.races``, ``.crashes``)."""
    sched = DeterministicScheduler(seed=seed, max_steps=max_steps)
    with sched.active():
        scenario(sched)
    if sched.crashes:
        name, exc = sched.crashes[0]
        raise SchedulerError(
            f"managed thread '{name}' crashed under seed {seed}: "
            f"{exc!r}") from exc
    return sched


def find_races(scenario: Callable[[DeterministicScheduler], None], *,
               schedules: int = 20, seed0: int = 0,
               max_steps: int = 200_000) -> list[RaceReport]:
    """Sweep ``schedules`` seeded interleavings of ``scenario`` and
    return every race found (empty = race-free within the budget)."""
    races: list[RaceReport] = []
    for i in range(schedules):
        sched = run_schedule(scenario, seed=seed0 + i, max_steps=max_steps)
        races.extend(sched.tracker.races)
    return races
