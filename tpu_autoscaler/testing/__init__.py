"""Test-side harnesses shipped with the package.

``tpu_autoscaler.testing.sched`` is layer 2 of the race detector
(docs/ANALYSIS.md): a deterministic scheduler that serializes the
control plane's threads through the ``tpu_autoscaler.concurrency`` seam
and permutes interleavings across seeded schedules while a vector-clock
happens-before checker watches shared state.
"""
