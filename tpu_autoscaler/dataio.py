"""Token-shard data loading for the in-tree trainer.

Runtime IO infrastructure for the workload side (the reference has no
data path — SURVEY §3): binary uint32 token shards served as
[batch, seq+1] next-token windows.

Two engines with bit-identical output:

- ``NativeTokenLoader`` — the C++ loader (native/tokenloader.cpp):
  mmap'd shard, double-buffered background prefetch so the next step's
  batch materializes while the device runs the current one.
- ``PyTokenLoader`` — pure numpy fallback (no compiler needed), same
  stateless splitmix64 sampling.

Sampling is a pure function of (seed, step, row): checkpoint resume
replays the exact stream with no loader state to persist, and the two
engines can be asserted equal row for row (tests/test_dataio.py).
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np

from tpu_autoscaler.native import load_native_lib

log = logging.getLogger(__name__)

_tl_cache: dict = {}

_MASK = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """Bit-identical twin of tokenloader.cpp::splitmix64."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return (x ^ (x >> 31)) & _MASK


def row_offset(seed: int, step: int, row: int, span: int) -> int:
    """Start offset of (step, row) — THE sampling rule, shared verbatim
    with the native loader (tokenloader.cpp::row_offset)."""
    return _splitmix64(seed ^ _splitmix64(step ^ _splitmix64(row))) % span


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a uint32 token shard (little-endian, the loaders' format)."""
    np.asarray(tokens, dtype="<u4").tofile(path)


def _configure_tokenloader(lib: ctypes.CDLL) -> None:
    lib.tl_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                            ctypes.c_int64, ctypes.c_uint64]
    lib.tl_open.restype = ctypes.c_int64
    lib.tl_next.argtypes = [ctypes.c_int64, ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_uint32)]
    lib.tl_next.restype = ctypes.c_int
    lib.tl_prefetch.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.tl_prefetch.restype = ctypes.c_int
    lib.tl_n_tokens.argtypes = [ctypes.c_int64]
    lib.tl_n_tokens.restype = ctypes.c_int64
    lib.tl_close.argtypes = [ctypes.c_int64]
    lib.tl_close.restype = ctypes.c_int


def _load_lib() -> ctypes.CDLL | None:
    return load_native_lib("libtokenloader.so",
                           configure=_configure_tokenloader,
                           cache=_tl_cache)


class PyTokenLoader:
    """Numpy reference engine (and no-toolchain fallback)."""

    def __init__(self, path: str, batch: int, window: int, seed: int = 0):
        if window < 2 or batch < 1:
            raise ValueError("window must be >= 2 and batch >= 1")
        self._tokens = np.memmap(path, dtype="<u4", mode="r")
        if self._tokens.size < window:
            raise ValueError(
                f"shard {path} has {self._tokens.size} tokens, need at "
                f"least one window of {window}")
        self.batch, self.window, self.seed = batch, window, seed
        self.n_tokens = int(self._tokens.size)

    def next(self, step: int) -> np.ndarray:
        span = self.n_tokens - self.window + 1
        out = np.empty((self.batch, self.window), np.uint32)
        for r in range(self.batch):
            off = row_offset(self.seed, step, r, span)
            out[r] = self._tokens[off:off + self.window]
        return out

    def close(self) -> None:
        self._tokens = None


class NativeTokenLoader:
    """ctypes front end of the C++ loader; raises if unavailable."""

    def __init__(self, path: str, batch: int, window: int, seed: int = 0):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native token loader unavailable")
        handle = lib.tl_open(path.encode(), window, batch, seed)
        if handle < 0:
            raise ValueError(
                f"tl_open({path!r}) failed with code {handle} (missing "
                f"file, or shard shorter than one window of {window})")
        self._lib, self._handle = lib, handle
        self.batch, self.window = batch, window
        self.n_tokens = int(lib.tl_n_tokens(handle))

    def next(self, step: int) -> np.ndarray:
        out = np.empty((self.batch, self.window), np.uint32)
        rc = self._lib.tl_next(
            self._handle, step,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        if rc != 0:
            raise RuntimeError(f"tl_next failed rc={rc}")
        # Overlap the NEXT step's fill with the device step.
        self._lib.tl_prefetch(self._handle, step + 1)
        return out

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tl_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def open_token_loader(path: str, batch: int, window: int, seed: int = 0):
    """Native when the toolchain allows, numpy otherwise — identical
    streams either way."""
    try:
        return NativeTokenLoader(path, batch, window, seed)
    except RuntimeError:
        return PyTokenLoader(path, batch, window, seed)
