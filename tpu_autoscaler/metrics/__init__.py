from tpu_autoscaler.metrics.metrics import Metrics

__all__ = ["Metrics"]
