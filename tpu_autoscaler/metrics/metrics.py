"""Structured metrics for the detection→actuation path.

The reference had only Python logging (SURVEY.md §6.1/6.5); the rebuild's
north-star metric *is* a latency, so per-phase timers are first-class:

- ``scale_up_latency_seconds``  — gang first seen Unschedulable → all pods
  Running (the BASELINE metric).
- ``decision_latency_seconds``  — observation → plan computed.
- ``provision_latency_seconds`` — provision submitted → slice ACTIVE.
- ``stranded_chips``            — per provisioned slice.

Export is Prometheus text format over a trivial HTTP handler (see
``serve``), plus a dict snapshot for tests and logs.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from tpu_autoscaler import concurrency


class _Summary:
    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.last = value

    def as_dict(self) -> dict:
        # Empty-case guard: before the first observe, min/max sit at
        # their +-inf sentinels — exporting them would leak "Infinity"
        # into every JSON rendering (strict parsers reject it) and into
        # any gauge-style export of the summary extrema.  An empty
        # summary exports count alone; every renderer keys off it.
        if not self.count:
            return {"count": 0}
        return {"count": self.count, "sum": self.total,
                "avg": self.total / self.count, "min": self.min,
                "max": self.max, "last": self.last}


class Metrics:
    def __init__(self):
        self._lock = concurrency.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._summaries: dict[str, _Summary] = defaultdict(_Summary)
        # name -> sorted upper bounds; observations on a declared name
        # additionally populate cumulative bucket counts so the north-star
        # phase latencies export as real Prometheus histograms (a cluster
        # run produces the BASELINE latency distribution directly, not
        # just count/sum/max).
        self._hist_buckets: dict[str, tuple[float, ...]] = {}
        self._hist_counts: dict[str, list[int]] = {}

    def declare_histogram(self, name: str,
                          buckets: tuple[float, ...]) -> None:
        with self._lock:
            bounds = tuple(sorted(buckets))
            if self._hist_buckets.get(name) == bounds:
                return
            self._hist_buckets[name] = bounds
            self._hist_counts[name] = [0] * len(bounds)

    def inc(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._summaries[name].observe(value)
            bounds = self._hist_buckets.get(name)
            if bounds:
                counts = self._hist_counts[name]
                for i, le in enumerate(bounds):
                    if value <= le:
                        counts[i] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "summaries": {k: s.as_dict()
                              for k, s in self._summaries.items()},
                "histograms": {
                    name: {"buckets": list(zip(bounds,
                                               self._hist_counts[name]))}
                    for name, bounds in self._hist_buckets.items()},
            }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (metric names sanitized).

        Every family gets ``# HELP`` + ``# TYPE`` lines (exposition-
        format contract; scrapers and promtool lint on them), not just
        histograms.  The HELP text points at the operator runbook —
        docs/OPERATIONS.md is the one place metric meanings live (and
        the TAO6xx checker keeps code and runbook in sync), so the
        exposition references it instead of duplicating prose.
        Empty-summary guard: min/max/sum render only after the first
        observation (see ``_Summary.as_dict``) — an idle process must
        never expose ``inf``/``-inf`` samples.
        """
        def clean(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_"
                           for c in name)

        def head(n: str, kind: str) -> list[str]:
            return [f"# HELP {n} tpu-autoscaler {n.replace('_', ' ')} "
                    f"(see docs/OPERATIONS.md)",
                    f"# TYPE {n} {kind}"]

        lines = []
        snap = self.snapshot()
        for name, v in sorted(snap["counters"].items()):
            lines += head(clean(name), "counter")
            lines.append(f"{clean(name)} {v}")
        for name, v in sorted(snap["gauges"].items()):
            lines += head(clean(name), "gauge")
            lines.append(f"{clean(name)} {v}")
        hists = snap.get("histograms", {})
        for name, s in sorted(snap["summaries"].items()):
            n = clean(name)
            if name in hists:
                continue  # exported as a histogram below
            lines += head(n, "summary")
            lines.append(f"{n}_count {s.get('count', 0)}")
            if s.get("count"):
                lines.append(f"{n}_sum {s['sum']}")
                # Extrema ride as their OWN gauge families: _min/_max
                # are not summary-family samples, so hiding them under
                # the summary TYPE would make promtool flag undeclared
                # series (empty-case guarded: absent before the first
                # observe, never inf).
                lines += head(f"{n}_min", "gauge")
                lines.append(f"{n}_min {s['min']}")
                lines += head(f"{n}_max", "gauge")
                lines.append(f"{n}_max {s['max']}")
        for name, h in sorted(hists.items()):
            n = clean(name)
            s = snap["summaries"].get(name, {})
            count = s.get("count", 0)
            lines += head(n, "histogram")
            for le, cum in h["buckets"]:
                lines.append(f'{n}_bucket{{le="{le:g}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{n}_sum {s.get('sum', 0.0)}")
            lines.append(f"{n}_count {count}")
        return "\n".join(lines) + "\n"

    def serve(self, port: int, debugz=None, routes=None) -> threading.Thread:
        """Serve /metrics (+ /healthz, + /debugz) on a daemon thread.

        ``debugz``: optional zero-arg callable returning a JSON-able
        dict — the flight-recorder dump (``Controller.debug_dump``), so
        a stuck production controller can be inspected over the port it
        already exposes, without a restart (docs/OBSERVABILITY.md).
        Serialized with ``allow_nan=False``: an ``inf`` anywhere in the
        dump is a bug (empty-summary guard) and must fail loudly here,
        not in whichever strict JSON parser reads the dump later.

        ``routes``: extra JSON endpoints — path → callable(params)
        where params is the parsed query string (first value per key).
        How ``/debugz/tsdb`` (``Controller.tsdb_route``) rides the
        port operators already expose, same serialization contract.

        Every port self-describes: ``/debugz/index`` lists the
        registered routes (ISSUE 11 satellite) so an operator
        discovers ``/debugz/cost`` or ``/debugz/tsdb`` from the port
        itself instead of from OBSERVABILITY.md.
        """
        import http.server
        import json
        import urllib.parse

        metrics = self
        routes = dict(routes or {})
        index = sorted({"/metrics", "/healthz", "/debugz/index"}
                       | set(routes)
                       | ({"/debugz"} if debugz is not None else set()))
        routes.setdefault("/debugz/index", lambda params: {
            "routes": index})

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                path, _, query = self.path.partition("?")
                if path in routes:
                    params = {k: v[0] for k, v in
                              urllib.parse.parse_qs(query).items()}
                    body = json.dumps(routes[path](params), indent=2,
                                      default=str,
                                      allow_nan=False).encode()
                    ctype = "application/json"
                elif path == "/debugz" and debugz is not None:
                    body = json.dumps(debugz(), indent=2, default=str,
                                      allow_nan=False).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = metrics.render_prometheus().encode()
                    # The Prometheus exposition-format content type.
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain; charset=utf-8"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        # The actually-bound port (stable even with port=0, which lets
        # tests and co-located processes avoid collisions).  Published
        # under the lock: serve() may be called while scrapes are
        # already running (thread discipline, TAT201).
        with self._lock:
            self.bound_port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return thread
